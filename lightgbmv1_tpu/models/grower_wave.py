"""Wave-K best-first tree growth — the TPU-native leaf-wise schedule.

The reference grows leaf-wise strictly sequentially: pick the single
frontier leaf with the best gain, split it, histogram the smaller child,
repeat ``num_leaves - 1`` times (``SerialTreeLearner::Train``,
src/treelearner/serial_tree_learner.cpp:152-202).  That schedule is hostile
to a TPU: each step is a tiny histogram job (3 MXU rows) plus a dynamic-size
partition, and the device pays a full dispatch-pipeline of latency per
split.

This module keeps the reference's *policy* — frontier leaves ranked by best
split gain, global across depths, stopped by the ``num_leaves`` budget and
positive-gain test (serial_tree_learner.cpp:192-195) — but changes the
*schedule*: each round splits the top-``K`` frontier leaves at once and
computes the histograms of all ``2K`` children in ONE batched device pass:

* the per-split ``DataPartition::Split`` scatter (data_partition.hpp:101)
  becomes one vectorized decision pass over all rows for all K splits,
* the smaller-child + subtraction trick (``BeforeFindBestSplit``
  serial_tree_learner.cpp:274-314, ``FeatureHistogram::Subtract``
  feature_histogram.hpp:79) is kept, batched: rows of the SMALLER child of
  each of the K splits are labeled with their slot and all K smaller-child
  histograms are built in one masked one-hot-matmul pass
  (ops/histogram.py); the larger children come from the per-leaf histogram
  state by subtraction.  This halves the MXU pass (K+1 slots instead of
  2K+1) and, in data-parallel mode, the histogram psum volume.  Wide-F
  configs whose (L, F, B, 3) state would exceed 512 MB fall back to the
  pool-free 2K-slot pass,
* split finding for the 2K children is one ``vmap`` of the vectorized scan
  (ops/split.py), the analog of ``FindBestSplitsFromHistograms``' OMP loop
  (serial_tree_learner.cpp:358-425).

At ``K = 1`` the schedule IS the reference's best-first order (one leaf per
round, ranked by argmax over the frontier) and reproduces the sequential
grower's trees split-for-split (both use parent subtraction; fp summation
noise can still flip exact near-ties, tests/test_wave_grower.py).  At ``K > 1`` the tree
can deviate from strict best-first only through the budget boundary: a
round commits its top-K leaves together, so children created inside the
round cannot displace the round's lower-ranked picks.  Rounds are
while-looped until the budget is exhausted or no frontier leaf has positive
gain — identical stopping semantics to the reference.

Distribution composes exactly like the sequential grower, but with one
collective per ROUND instead of per split: the data-parallel learner wraps
``hist_wave_fn`` in a ``lax.psum`` (the analog of the reference's
ReduceScatter of histograms, data_parallel_tree_learner.cpp:155-173), the
feature-/voting-parallel learners substitute ``split_fn``.

Quantized rounds (round 7): with ``hist_dtype_deep="int8sr"`` the
sustained bucket and the 16-slot ramp bucket of a K>16 wave run a
stochastic-rounded int8 histogram pass (ops/quantize.py + the int8 MXU
path of ops/hist_pallas.py); the pass returns INTEGER histograms plus
per-slot scales, and dequantization is folded into the smaller-child
subtraction (``subtract_child_hists(slot_scale=...)``) or handed to the
split scan (``find_best_split(hist_scale=...)``) — the histogram never
takes a separate dequantize round-trip.  Rounding is keyed per
(iteration, round) by folding the tree key with the round's leaf count,
so grown trees are bit-reproducible given the seed.

Async wave pipelining (round 12): the sequential round body ends with
commits the NEXT round only partially depends on — the per-leaf
histogram-state scatter and the valid-row routing — yet the
``lax.while_loop`` body boundary is a barrier, so they serialize against
the next round's critical path (top-k → partition decision → histogram
MXU pass → split scan) anyway.  With ``async_wave_pipeline`` (default)
those commits are DEFERRED one round through a pending carry: round r's
child-histogram stack + scatter indices + split metadata ride the carry,
and round r+1 issues the scatter and the valid routing inside ITS
computation, where the scheduler can overlap them with the MXU pass.
The subtraction's parent reads are value-forwarded (gather from the
one-round-stale table, patched from the pending stack — identical
values, no data dependence on the drained scatter), which also lets the
subtracted sibling's split scan start before the partition's leaf-id
reduction drains.  A post-loop drain applies the final round's routing,
so everything a caller (or a checkpoint) can observe is bit-identical
to the sequential schedule — pinned across binary/multiclass/DART in
tests/test_wave_pipeline.py; ``async_wave_pipeline=false`` keeps the
fully-serialized body as the pin.

Round bookkeeping (round 6): the per-leaf frontier state and the tree
arrays under construction live behind a store codec.  The default
``_PackedStore`` keeps them in two packed f32 tables committed with one
coalesced scatter each per round; ``_FieldStore`` is the legacy
one-array-per-field layout (~30 small scatters per round) kept for the
bit-parity test and attribution A/Bs (config ``fused_bookkeeping``).
The phase-attribution harness (tools/phase_attrib.py) measured the
legacy scatter storm as the dominant slice of the per-iteration
``phase_other_ms`` residual; both layouts grow bit-identical trees on
the exact-fp32 histogram path (tests/test_phase_attrib.py).
"""

from __future__ import annotations

import contextlib
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.split import (
    NO_CONSTRAINT,
    FeatureMeta,
    SplitParams,
    child_leaf_output,
    find_best_split,
    go_left_rule,
    leaf_output,
    smooth_output,
)
from .grower import _node_feature_mask, allowed_features_for
from .tree import TreeArrays, empty_tree

# Slot bucketing kicks in above this many rows: each extra bucket traces
# one more (S, N) partition + (S+1)-slot histogram variant, which is pure
# compile-time cost at test sizes (the CPU suite stays on the single
# full-wave path).  Lowered by tests to exercise the bucketed branches.
_BUCKET_MIN_N = 1 << 16

# Smaller-child + subtraction mode is skipped when the (L, F, B, 3)
# per-leaf histogram state would exceed this cap (wide-F configs fall back
# to the pool-free 2K-slot pass).  Module-level so tests can force the
# pool-free path on small shapes (e.g. the integer-domain voting
# collective only exists there, tests/test_parallel.py).
_SUB_STATE_CAP_BYTES = 512 * (1 << 20)


def replay_wave_schedule(trees, K: int):
    """Per-round split counts of the wave policy, replayed EXACTLY from
    grown trees' recorded structure + gains.

    The device ranks frontier leaves by best gain and commits the top-K
    per round; a leaf's ranking gain equals the ``split_gain`` recorded on
    the node it became, and every candidate that ever wins a budget race
    IS an internal node of the final tree — so replaying the ranked
    commit order over internal nodes reproduces the executed round
    grouping without any device round-trip (the axon runtime does not
    support jax.debug callbacks; _ROUND_PROBE covers CPU runs and the
    parity test ties the two together, tests/test_wave_bucket.py).
    Caveats: fp-equal gain ties replay by node index (the device breaks
    ties by leaf index), and the intermediate-monotone same-round
    deferral is not modeled — neither occurs in the bench configs."""
    out = []
    for t in trees:
        gains = np.asarray(t.split_gain)
        lc = np.asarray(t.left_child)
        rc = np.asarray(t.right_child)
        if int(t.num_leaves) <= 1:
            out.append([])
            continue
        sched = []
        cand = [0]
        while cand:
            cand.sort(key=lambda n: (-gains[n], n))
            take, cand = cand[:K], cand[K:]
            sched.append(len(take))
            cand += [int(c) for n in take for c in (lc[n], rc[n]) if c >= 0]
        out.append(sched)
    return out


def auto_wave_size(num_leaves: int) -> int:
    """The auto (leafwise_wave_size=0) wave size policy — num_leaves // 4
    (measured optimum with the smaller-child subtraction pass, PERF.md).
    Single source of truth for the trainer AND bench.py's round-schedule
    replay/pricing (a mismatched K would silently re-derive the wrong
    schedule)."""
    return max(1, num_leaves // 4)


def slot_buckets_for(K: int, N: int):
    """The wave grower's slot-bucket ladder for wave size ``K`` over ``N``
    rows — the single source of truth, shared with bench.py's round-cost
    derivation (each probed round is priced at its bucket's measured pass
    time)."""
    if K > 4 and N >= _BUCKET_MIN_N:
        return sorted({4, min(16, K), K})
    return [K]

# Optional host callback fired once per EXECUTED wave round with the
# round's realized split count (jax.debug.callback in the while-loop
# body).  bench.py sets this on a probe model to record the ACTUAL
# rounds-per-tree schedule behind `wave_rounds_per_tree` and the per-iter
# histogram cost — the counting role of the reference's USE_TIMETAG global
# timers (include/LightGBM/utils/common.h:1054-1138).  None (the default)
# adds nothing to the traced program.
_ROUND_PROBE = None


def _box_adjacency_per_feature(lo, hi, feats):
    """Yield ``(f, adj_up, adj_dn)`` pairwise adjacency matrices for leaf
    boxes along each feature in ``feats``: A→B adjacent-up along f means
    hi_A[f] == lo_B[f] with the boxes overlapping in EVERY other feature.
    Overlap counts are accumulated in feature blocks so peak residency is
    (L, L, 256), not (L, L, F).  Shared by the per-round constraint
    recomputation and the same-round split deferral so the adjacency
    definition cannot drift between them."""
    L, F = lo.shape
    ov_cnt = jnp.zeros((L, L), jnp.int32)
    FB = 256
    for c0 in range(0, F, FB):
        c1 = min(c0 + FB, F)
        ovb = (lo[:, None, c0:c1] < hi[None, :, c0:c1]) & \
              (lo[None, :, c0:c1] < hi[:, None, c0:c1])
        ov_cnt = ov_cnt + ovb.sum(axis=2).astype(jnp.int32)
    for f in feats:
        ov_f = (lo[:, None, f] < hi[None, :, f]) & \
               (lo[None, :, f] < hi[:, None, f])
        other = (ov_cnt - ov_f.astype(jnp.int32)) == (F - 1)
        adj_up = (hi[:, None, f] == lo[None, :, f]) & other
        adj_dn = (lo[:, None, f] == hi[None, :, f]) & other
        yield f, adj_up, adj_dn


def intermediate_constraints(boxes, outs, num_leaves, mono_feats,
                             mono_types):
    """Vectorized re-design of the reference's IntermediateLeafConstraints
    (src/treelearner/monotone_constraints.hpp:125-310).

    The reference walks the tree recursively after every split
    (GoUpToFindLeavesToUpdate / GoDownToFindLeavesToUpdate) to find leaves
    whose region is CONTIGUOUS to the new children along a monotone feature
    and tightens their bounds against the new outputs.  Here every leaf
    carries its bin-space box (``boxes`` (L, F, 2) [lo, hi)), and all
    constraints are recomputed from scratch each round as a pairwise
    adjacency reduction: leaf A's upper bound along an increasing feature f
    is the min output over leaves adjacent above it (hi_A[f] == lo_B[f],
    overlapping in every other feature) — O(L²·F) vectorized ops, trivial
    per round, no recursion.  Bounds come from neighbouring leaf OUTPUTS
    instead of the basic mode's split midpoints, which is the point of the
    intermediate mode: tighter leaves, better gains.
    """
    L, F, _ = boxes.shape
    lo = boxes[..., 0]
    hi = boxes[..., 1]
    iota = jnp.arange(L, dtype=jnp.int32)
    valid_b = (iota[None, :] < num_leaves) & (iota[:, None] != iota[None, :])
    max_c = jnp.full(L, NO_CONSTRAINT[1], jnp.float32)
    min_c = jnp.full(L, NO_CONSTRAINT[0], jnp.float32)
    types = dict(zip(mono_feats, mono_types))
    for f, adj_up, adj_dn in _box_adjacency_per_feature(lo, hi, mono_feats):
        adj_up = adj_up & valid_b
        adj_dn = adj_dn & valid_b
        if types[f] < 0:           # decreasing: roles of up/down swap
            adj_up, adj_dn = adj_dn, adj_up
        max_c = jnp.minimum(max_c, jnp.min(
            jnp.where(adj_up, outs[None, :], jnp.inf), axis=1))
        min_c = jnp.maximum(min_c, jnp.max(
            jnp.where(adj_dn, outs[None, :], -jnp.inf), axis=1))
    return jnp.stack([min_c, max_c], axis=1)           # (L, 2)


class WaveState(NamedTuple):
    leaf_id: jax.Array        # (N,) int32 — current leaf of every row
    valid_lids: tuple         # per valid set: (Nv,) int32 leaf of every
                              # VALID row, routed through the same per-round
                              # decisions — valid-set score updates become a
                              # leaf_value gather instead of a per-tree
                              # root-to-leaf walk; () when no valid sets
    leaf_hist: jax.Array      # (L, F, B, 3) — per-leaf histograms enabling
                              # the smaller-child + subtraction trick
                              # (reference BeforeFindBestSplit +
                              # FeatureHistogram::Subtract); (1, F, B, 3)
                              # dummy when the state would exceed the cap
    store: dict               # codec-owned frontier + tree bookkeeping —
                              # _PackedStore (fused, two coalesced tables)
                              # or _FieldStore (legacy per-field arrays)
    leaf_box: jax.Array       # (L, F, 2) — bin-space region per leaf
                              # (intermediate monotone mode; (1, 1, 2) dummy)
    leaf_used: jax.Array      # (L, F) bool — branch features; (1, 1) dummy
                              # unless interaction constraints are on
    num_leaves: jax.Array     # () int32
    done: jax.Array           # () bool
    pending: dict = {}        # async_wave_pipeline: the previous round's
                              # DEFERRED commits — the (2K, F, B, 3) child
                              # histograms + their scatter indices and the
                              # (K,) split metadata for the valid-row
                              # routing — applied at the START of the next
                              # body (or by the post-loop drain), where the
                              # scheduler can overlap them with that
                              # round's partition + histogram pass; {} on
                              # the sequential path


def subtract_child_hists(h_slot, leaf_hist, leafs, order_c, sm_left,
                         slot_scale=None, h_parent=None):
    """Smaller-child + parent-subtraction child histograms of one wave
    round (reference BeforeFindBestSplit smaller-leaf trick +
    FeatureHistogram::Subtract): ``h_slot`` holds the measured smaller
    children in slot order; the larger sibling is the stored parent
    histogram minus the smaller.  Returns the rank-order interleaved
    ``(2K, F, B, 3)`` child stack plus the separate left/right halves.
    Module-level so tools/phase_attrib.py can time exactly the ops the
    grower's round body runs.

    ``slot_scale`` (K, 3): when the round's histogram pass ran quantized
    (stochastic-rounded int8, ops/quantize.py), ``h_slot`` carries exact
    integer counts and the per-slot dequantization is folded HERE — one
    broadcast multiply fused into the gather/subtract pipeline the round
    already pays, so the kernel never writes a dequantized copy and the
    quantized histogram is read from HBM exactly once.

    ``h_parent`` (K, F, B, 3): pre-gathered parent histograms — the
    pipelined schedule passes the value-forwarded rows (one-round-stale
    table patched from the pending commit) so the subtraction never waits
    on the deferred scatter; None gathers from ``leaf_hist`` as before."""
    h_small = h_slot[order_c]              # slot-order -> rank-order
    if slot_scale is not None:
        # exact multiply: every dequantization scale is a power of two
        # (ops/quantize.sr_prequantize_g3), so the subtraction below
        # rounds identically whether or not the compiler contracts this
        # product into it (fma) — the bit-parity contract between this
        # site, the fused kernel's scan, and the wave-loop commit
        # depends on that exactness, not on fusion heuristics.
        h_small = h_small * slot_scale[order_c][:, None, None, :]
    if h_parent is None:
        h_parent = leaf_hist[leafs]
    smL = sm_left[:, None, None, None]
    h_left = jnp.where(smL, h_small, h_parent - h_small)
    h_right = h_parent - h_left
    hist = jnp.stack([h_left, h_right], axis=1).reshape(
        (2 * h_left.shape[0],) + h_left.shape[1:])
    return hist, h_left, h_right


# ---------------------------------------------------------------------------
# Per-round bookkeeping stores.
#
# The round body computes one set of values either way; the store decides
# HOW they are kept between rounds.  tools/phase_attrib.py instantiates
# both stores directly to time their write paths — the same code objects
# the grower's while-loop body calls.
# ---------------------------------------------------------------------------


class _FieldStore:
    """Legacy (unfused) bookkeeping: every frontier / tree field is its
    own array and every round writes each with its own K- or 2K-row
    scatter (~30 small scatters per round).  Selectable via
    ``fused_bookkeeping=false`` — the reference layout for the
    fused-vs-unfused bit-parity test (tests/test_phase_attrib.py) and for
    attribution A/Bs."""

    fused = False

    def __init__(self, L, L1, W, use_mc, use_cat):
        self.L, self.L1, self.W = L, L1, W
        self.use_mc, self.use_cat = use_mc, use_cat

    def init(self, res0, out0):
        L, W = self.L, self.W
        return dict(
            best_gain=jnp.full(L, -jnp.inf, jnp.float32).at[0]
            .set(res0.gain),
            best_feat=jnp.zeros(L, jnp.int32).at[0].set(res0.feature),
            best_bin=jnp.zeros(L, jnp.int32).at[0].set(res0.threshold_bin),
            best_dl=jnp.zeros(L, bool).at[0].set(res0.default_left),
            best_left=jnp.zeros((L, 3), jnp.float32).at[0]
            .set(res0.left_sum),
            best_right=jnp.zeros((L, 3), jnp.float32).at[0]
            .set(res0.right_sum),
            best_iscat=jnp.zeros(L, bool).at[0].set(res0.is_cat),
            best_bitset=jnp.zeros((L, W), jnp.uint32).at[0]
            .set(res0.cat_bitset),
            leaf_constr=jnp.tile(jnp.asarray(NO_CONSTRAINT, jnp.float32),
                                 (L, 1)),
            leaf_out=jnp.zeros(L, jnp.float32).at[0].set(out0),
            leaf_depth=jnp.zeros(L, jnp.int32),
            leaf_is_left=jnp.zeros(L, bool),
            tree=empty_tree(L, W),
        )

    def gains(self, s):
        return s["best_gain"]

    def leaf_out_full(self, s):
        return s["leaf_out"]

    def read(self, s, leafs):
        t = s["tree"]
        return dict(
            feats=s["best_feat"][leafs],
            thrs=s["best_bin"][leafs],
            dls=s["best_dl"][leafs],
            iscats=s["best_iscat"][leafs],
            bitsets=s["best_bitset"][leafs],
            lsums=s["best_left"][leafs],
            rsums=s["best_right"][leafs],
            pconstr=s["leaf_constr"][leafs],
            pout=s["leaf_out"][leafs],
            pdepth=s["leaf_depth"][leafs],
            was_left=s["leaf_is_left"][leafs],
            parent=t.leaf_parent[leafs],
        )

    def write(self, s, r):
        res = r["res"]
        t = s["tree"]
        lc = t.left_child.at[r["fix_l"]].set(r["nidx"], mode="drop")
        rc = t.right_child.at[r["fix_r"]].set(r["nidx"], mode="drop")
        lc = lc.at[r["nidx"]].set(-(r["leafs"] + 1), mode="drop")
        rc = rc.at[r["nidx"]].set(-(r["nls"] + 1), mode="drop")
        tree = t._replace(
            num_leaves=r["num_leaves_new"],
            split_feature=t.split_feature.at[r["nidx"]]
            .set(r["feats"], mode="drop"),
            threshold_bin=t.threshold_bin.at[r["nidx"]]
            .set(r["thrs"], mode="drop"),
            default_left=t.default_left.at[r["nidx"]]
            .set(r["dls"], mode="drop"),
            is_cat=t.is_cat.at[r["nidx"]].set(r["iscats"], mode="drop"),
            cat_bitset=t.cat_bitset.at[r["nidx"]]
            .set(r["bitsets"], mode="drop"),
            missing_type=t.missing_type.at[r["nidx"]]
            .set(r["mtypes"], mode="drop"),
            left_child=lc,
            right_child=rc,
            split_gain=t.split_gain.at[r["nidx"]]
            .set(r["vals"], mode="drop"),
            internal_value=t.internal_value.at[r["nidx"]]
            .set(r["pout"], mode="drop"),
            internal_weight=t.internal_weight.at[r["nidx"]]
            .set(r["psum"][:, 1], mode="drop"),
            internal_count=t.internal_count.at[r["nidx"]]
            .set(r["psum"][:, 2], mode="drop"),
            leaf_value=t.leaf_value.at[r["lidx"]]
            .set(r["out_l"], mode="drop")
            .at[r["nlidx"]].set(r["out_r"], mode="drop"),
            leaf_weight=t.leaf_weight.at[r["lidx"]]
            .set(r["lsums"][:, 1], mode="drop")
            .at[r["nlidx"]].set(r["rsums"][:, 1], mode="drop"),
            leaf_count=t.leaf_count.at[r["lidx"]]
            .set(r["lsums"][:, 2], mode="drop")
            .at[r["nlidx"]].set(r["rsums"][:, 2], mode="drop"),
            leaf_parent=t.leaf_parent.at[r["lidx"]]
            .set(r["nidx"], mode="drop")
            .at[r["nlidx"]].set(r["nidx"], mode="drop"),
        )
        cidx = r["cidx"]
        return dict(
            best_gain=s["best_gain"].at[cidx].set(r["cgain"], mode="drop"),
            best_feat=s["best_feat"].at[cidx]
            .set(res.feature, mode="drop"),
            best_bin=s["best_bin"].at[cidx]
            .set(res.threshold_bin, mode="drop"),
            best_dl=s["best_dl"].at[cidx]
            .set(res.default_left, mode="drop"),
            best_left=s["best_left"].at[cidx]
            .set(res.left_sum, mode="drop"),
            best_right=s["best_right"].at[cidx]
            .set(res.right_sum, mode="drop"),
            best_iscat=s["best_iscat"].at[cidx]
            .set(res.is_cat, mode="drop"),
            best_bitset=s["best_bitset"].at[cidx]
            .set(res.cat_bitset, mode="drop"),
            leaf_constr=s["leaf_constr"].at[cidx]
            .set(r["cconstr"], mode="drop"),
            leaf_out=s["leaf_out"].at[cidx].set(r["couts"], mode="drop"),
            leaf_depth=s["leaf_depth"].at[cidx]
            .set(r["cdepth"], mode="drop"),
            leaf_is_left=s["leaf_is_left"].at[r["lidx"]]
            .set(True, mode="drop")
            .at[r["nlidx"]].set(False, mode="drop"),
            tree=tree,
        )

    def finalize(self, s, num_leaves):
        return s["tree"]._replace(num_leaves=num_leaves)


class _PackedStore:
    """Fused per-round bookkeeping (``fused_bookkeeping=true``, default).

    All per-leaf frontier + tree-leaf state lives in ONE ``(L, CF)`` f32
    table and all per-node tree state in ONE ``(L1, 10)`` f32 table.  A
    round commits with one coalesced 2K-row scatter into the frontier
    table, one K-row scatter into the node table, and one two-column
    child-pointer fixup — three scatters instead of the legacy layout's
    ~30 per-field scatters per round (the phase-attribution harness
    measured that scatter storm as the largest slice of the
    per-iteration ``phase_other_ms`` residual, tools/phase_attrib.py).

    Integers and booleans ride as exact small f32 values (every id, bin,
    depth and child index is far below 2^24), so packing is bit-lossless
    and the grown trees are bit-identical to the unfused layout on the
    exact-fp32 histogram path (tests/test_phase_attrib.py pins this).
    Categorical state (uint32 bitsets) keeps separate arrays — f32
    storage cannot carry arbitrary 32-bit patterns by value — and the
    monotone constraint bounds add two columns only when constraints are
    on, so the common no-cat/no-mono config pays for neither."""

    fused = True

    # frontier-table columns (per leaf)
    GAIN, FEAT, BIN, DL = 0, 1, 2, 3
    LS, RS = 4, 7                    # [4:7) left sums, [7:10) right sums
    OUT, DEPTH, ISLEFT = 10, 11, 12
    LVAL, LWEIGHT, LCNT, LPAR = 13, 14, 15, 16
    CMIN, CMAX = 17, 18              # only materialized when use_mc
    # node-table columns (per internal node)
    NFEAT, NBIN, NDL, NMT, NGAIN, NIVAL, NIW, NIC, NLC, NRC = range(10)

    def __init__(self, L, L1, W, use_mc, use_cat):
        self.L, self.L1, self.W = L, L1, W
        self.use_mc, self.use_cat = use_mc, use_cat
        self.CF = 19 if use_mc else 17

    def init(self, res0, out0):
        L, L1, W = self.L, self.L1, self.W
        z = jnp.float32(0.0)
        ft = jnp.zeros((L, self.CF), jnp.float32)
        ft = ft.at[:, self.GAIN].set(-jnp.inf)
        ft = ft.at[:, self.LPAR].set(-1.0)
        if self.use_mc:
            ft = ft.at[:, self.CMIN].set(float(NO_CONSTRAINT[0]))
            ft = ft.at[:, self.CMAX].set(float(NO_CONSTRAINT[1]))
        root = jnp.stack([
            res0.gain,
            res0.feature.astype(jnp.float32),
            res0.threshold_bin.astype(jnp.float32),
            res0.default_left.astype(jnp.float32),
            res0.left_sum[0], res0.left_sum[1], res0.left_sum[2],
            res0.right_sum[0], res0.right_sum[1], res0.right_sum[2],
            out0, z, z, z, z, z, jnp.float32(-1.0),
        ] + ([jnp.float32(NO_CONSTRAINT[0]),
              jnp.float32(NO_CONSTRAINT[1])] if self.use_mc else []))
        ft = ft.at[0].set(root)
        nt = jnp.zeros((L1, 10), jnp.float32)
        nt = nt.at[:, self.NLC].set(-1.0).at[:, self.NRC].set(-2.0)
        out = {"ft": ft, "nt": nt}
        if self.use_cat:
            out["f_iscat"] = jnp.zeros(L, bool).at[0].set(res0.is_cat)
            out["f_bitset"] = jnp.zeros((L, W), jnp.uint32).at[0] \
                .set(res0.cat_bitset)
            out["n_iscat"] = jnp.zeros(L1, bool)
            out["n_bitset"] = jnp.zeros((L1, W), jnp.uint32)
        return out

    def gains(self, s):
        return s["ft"][:, self.GAIN]

    def leaf_out_full(self, s):
        return s["ft"][:, self.OUT]

    def read(self, s, leafs):
        rows = s["ft"][leafs]                      # ONE gather for all fields
        K = leafs.shape[0]
        return dict(
            feats=rows[:, self.FEAT].astype(jnp.int32),
            thrs=rows[:, self.BIN].astype(jnp.int32),
            dls=rows[:, self.DL] != 0,
            lsums=rows[:, self.LS:self.LS + 3],
            rsums=rows[:, self.RS:self.RS + 3],
            pout=rows[:, self.OUT],
            pdepth=rows[:, self.DEPTH].astype(jnp.int32),
            was_left=rows[:, self.ISLEFT] != 0,
            parent=rows[:, self.LPAR].astype(jnp.int32),
            pconstr=(rows[:, self.CMIN:self.CMAX + 1] if self.use_mc
                     else jnp.tile(jnp.asarray(NO_CONSTRAINT, jnp.float32),
                                   (K, 1))),
            iscats=(s["f_iscat"][leafs] if self.use_cat
                    else jnp.zeros(K, bool)),
            bitsets=(s["f_bitset"][leafs] if self.use_cat
                     else jnp.zeros((K, self.W), jnp.uint32)),
        )

    def write(self, s, r):
        res = r["res"]
        n2 = r["cidx"].shape[0]                    # 2K
        K = n2 // 2
        # -- frontier + tree-leaf state: ONE coalesced 2K-row scatter ----
        crows = jnp.concatenate([
            r["cgain"][:, None],
            res.feature.astype(jnp.float32)[:, None],
            res.threshold_bin.astype(jnp.float32)[:, None],
            res.default_left.astype(jnp.float32)[:, None],
            res.left_sum, res.right_sum,
            r["couts"][:, None],
            r["cdepth"].astype(jnp.float32)[:, None],
            jnp.tile(jnp.asarray([1.0, 0.0], jnp.float32), K)[:, None],
            r["couts"][:, None],                  # leaf_value == leaf_out
            r["csums"][:, 1:2], r["csums"][:, 2:3],
            jnp.stack([r["nidx"], r["nidx"]], axis=1).reshape(n2)
            .astype(jnp.float32)[:, None],
        ] + ([r["cconstr"]] if self.use_mc else []), axis=1)
        ft = s["ft"].at[r["cidx"]].set(crows, mode="drop")
        # -- node state: one K-row scatter + one 2-column pointer fixup --
        nrows = jnp.concatenate([
            r["feats"].astype(jnp.float32)[:, None],
            r["thrs"].astype(jnp.float32)[:, None],
            r["dls"].astype(jnp.float32)[:, None],
            r["mtypes"].astype(jnp.float32)[:, None],
            r["vals"][:, None],
            r["pout"][:, None],
            r["psum"][:, 1:2], r["psum"][:, 2:3],
            (-(r["leafs"] + 1)).astype(jnp.float32)[:, None],
            (-(r["nls"] + 1)).astype(jnp.float32)[:, None],
        ], axis=1)
        nt = s["nt"]
        # parents are strictly OLDER nodes than this round's new rows, so
        # the fixup and the row write never collide and order is free
        rows2 = jnp.concatenate([r["fix_l"], r["fix_r"]])
        cols2 = jnp.concatenate([jnp.full(K, self.NLC, jnp.int32),
                                 jnp.full(K, self.NRC, jnp.int32)])
        vals2 = jnp.concatenate([r["nidx"], r["nidx"]]).astype(jnp.float32)
        nt = nt.at[rows2, cols2].set(vals2, mode="drop")
        nt = nt.at[r["nidx"]].set(nrows, mode="drop")
        out = {"ft": ft, "nt": nt}
        if self.use_cat:
            out["f_iscat"] = s["f_iscat"].at[r["cidx"]] \
                .set(res.is_cat, mode="drop")
            out["f_bitset"] = s["f_bitset"].at[r["cidx"]] \
                .set(res.cat_bitset, mode="drop")
            out["n_iscat"] = s["n_iscat"].at[r["nidx"]] \
                .set(r["iscats"], mode="drop")
            out["n_bitset"] = s["n_bitset"].at[r["nidx"]] \
                .set(r["bitsets"], mode="drop")
        return out

    def finalize(self, s, num_leaves):
        ft, nt = s["ft"], s["nt"]
        L1, W = self.L1, self.W
        return TreeArrays(
            num_leaves=num_leaves,
            split_feature=nt[:, self.NFEAT].astype(jnp.int32),
            threshold_bin=nt[:, self.NBIN].astype(jnp.int32),
            threshold=jnp.zeros(L1, jnp.float32),
            default_left=nt[:, self.NDL] != 0,
            missing_type=nt[:, self.NMT].astype(jnp.int32),
            left_child=nt[:, self.NLC].astype(jnp.int32),
            right_child=nt[:, self.NRC].astype(jnp.int32),
            split_gain=nt[:, self.NGAIN],
            internal_value=nt[:, self.NIVAL],
            internal_weight=nt[:, self.NIW],
            internal_count=nt[:, self.NIC],
            leaf_value=ft[:, self.LVAL],
            leaf_weight=ft[:, self.LWEIGHT],
            leaf_count=ft[:, self.LCNT],
            leaf_parent=ft[:, self.LPAR].astype(jnp.int32),
            is_cat=(s["n_iscat"] if self.use_cat
                    else jnp.zeros(L1, bool)),
            cat_bitset=(s["n_bitset"] if self.use_cat
                        else jnp.zeros((L1, W), jnp.uint32)),
        )


def _topk_by_rank(gains: jax.Array, K: int):
    """Top-K (descending, ties by lower index — lax.top_k semantics) via an
    O(L²) rank matrix instead of lax.top_k: on TPU the sort-based top_k
    lowering costs ~13 ms even on a 255-element array, while this is a
    handful of vectorized compares (L ≤ a few thousand here)."""
    L = gains.shape[0]
    iota = jnp.arange(L, dtype=jnp.int32)
    g_l = gains[:, None]
    g_i = gains[None, :]
    beats = (g_l > g_i) | ((g_l == g_i) & (iota[:, None] < iota[None, :]))
    rank = jnp.sum(beats, axis=0).astype(jnp.int32)          # (L,)
    jk = jnp.arange(K, dtype=jnp.int32)
    sel = rank[None, :] == jk[:, None]                       # (K, L)
    leafs = jnp.sum(jnp.where(sel, iota[None, :], 0), axis=1)
    vals = jnp.sum(jnp.where(sel, gains[None, :], 0.0), axis=1)
    # rows whose rank never matched (can't happen: ranks are a permutation)
    return vals, leafs


def make_wave_grower(
    *,
    num_leaves: int,
    num_bins: int,
    meta: FeatureMeta,
    params: SplitParams,
    max_depth: int = -1,
    feature_fraction_bynode: float = 1.0,
    monotone_penalty: float = 0.0,
    monotone_mode: str = "basic",
    interaction_groups=None,
    wave_size: int = 32,
    fused_bookkeeping: bool = True,
    async_wave_pipeline: bool = True,
    hist_wave_fn: Callable = None,
    hist_wave_quant_fn: Callable = None,
    split_fn: Callable = None,
    sums_fn: Callable = None,
    bins_of_fn: Callable = None,
    fused_round_fn: Callable = None,
    fused_loop_fn: Callable = None,
):
    """Build the jittable ``grow(binned, g3, base_mask, key)`` function.

    ``hist_wave_fn(binned, g3, label, nslots, deep=False) ->
    (nslots, F, B, 3)`` — histograms of the rows labeled ``0..nslots-1``
    (label ``nslots`` = dead); globally summed in distributed mode.
    ``deep=True`` marks a sustained (largest-bucket) round of a big wave —
    the implementation may drop to the configured cheaper histogram dtype
    there (config.hist_dtype_deep).
    ``hist_wave_quant_fn(binned, g3, label, nslots, key) ->
    ((nslots, F, B, 3), (nslots, 3))`` — optional stochastic-rounded
    quantized pass (hist_dtype_deep="int8sr"): integer histogram plus
    per-slot dequant scales (all-ones when the implementation already
    dequantized, e.g. the data-parallel dequantize-then-psum wrapper).
    Eligible rounds — the sustained largest bucket (the existing deep
    gate) AND the 16-slot ramp bucket of a K>16 wave (VERDICT r5 priced
    ramp rounds at 11.7 ms vs 7.7 deep: the 16-slot bucket is the next
    harvest) — route here with a per-round fold-in of the tree key, so
    the rounding stream is deterministic per (iteration, round).  The
    root pass and the small (<=4 slot) ramp buckets NEVER quantize:
    their per-bin sums are large and precision-critical, and their cost
    is dispatch-dominated anyway.
    ``split_fn(hist, parent, mask, key, uid, constraint, depth,
    parent_output) -> SplitResult`` — vmapped over the 2K children.
    ``sums_fn(g3) -> (3,)`` — root totals (psum over the row axis when
    data-parallel).
    ``bins_of_fn(binned, feat) -> (N,)`` — ORIGINAL bins of a feature; the
    EFB path substitutes the bundle-column decode (io/bundle.py
    bundle_bins_of_feat), so ``binned`` may be the (BF, N) bundled matrix.
    ``fused_bookkeeping`` selects the per-round state layout: packed
    tables with one coalesced scatter each (_PackedStore, default) or the
    legacy per-field scatters (_FieldStore); trees are bit-identical
    either way on the exact-fp32 histogram path.
    ``fused_round_fn`` (ops/wave_fused.make_fused_round, wired by
    parallel/trainer.py under ``hist_method=fused``): the wave rounds'
    histogram pass + smaller-child subtraction + split scan collapse
    into ONE fused kernel call per slot bucket — the kernel accumulates
    the slot histograms in VMEM, subtracts the parent stack it reads as
    an input, runs the staged scan's own stage functions on the VMEM
    values and returns only the packed per-child SplitInfo (plus, in
    subtraction mode, the smaller-child histograms the per-leaf state
    scatter needs).  The staged ``hist_wave_fn`` still runs the root
    pass, and ``hist_wave_quant_fn``'s PRESENCE still gates the int8sr
    buckets — the fused path quantizes through the same
    ``sr_quantize_g3`` stream, so the (iteration, round) determinism
    contract and the root/ramp never-quantize rule are shared, not
    re-implemented.  A ROUTING-CAPABLE ``fused_round_fn``
    (``supports_route`` + the ``route_rows`` valid-set router, ISSUE
    15) additionally folds the round's PARTITION into the kernel: the
    staged (S, N) decision pass is skipped, the kernel returns the
    updated per-row leaf ids from the same sweep that accumulates the
    histograms, the O(L) top-k and the dispatch run under one
    ``lgbm.fused_round`` label, and the valid sets (in-round or the
    pipelined drain) ride the kernel's decision stage instead of the
    staged gather chain — the round reads the binned rows ONCE.
    Trees are bit-identical to the staged path on the
    same histogram arithmetic (tests/test_wave_fused.py pins this in
    interpret mode).
    ``fused_loop_fn`` (ops/wave_fused.make_fused_wave_loop, wired by
    parallel/trainer.py under ``wave_loop_rounds > 1``): each while-loop
    body becomes a SEGMENT of R consecutive rounds run by ONE persistent
    kernel launch — frontier table, histogram pool and row→leaf labels
    resident in VMEM between rounds — followed by a host REPLAY of the R
    rounds' bookkeeping (store writes, valid routing, done flag) from
    the kernel's per-round packed SplitInfo.  Engagement is static
    (``fused_loop_fn.plan``, the VMEM budget planner) and falls back to
    the single-round body when ineligible; trees, stores and routings
    are bit-identical to both the single-round fused and the staged
    paths (tests/test_wave_fused.py's loop parity matrix).
    ``async_wave_pipeline`` (default on) software-pipelines the round
    loop: the per-leaf histogram-state scatter and the valid-row routing
    of round r are DEFERRED into a pending carry and applied at the
    start of round r+1 — off round r+1's critical path (top-k →
    partition decision → histogram MXU pass → split scan), so the
    scheduler can overlap them with it instead of serializing at the
    while-loop body barrier.  The subtraction's parent-histogram read is
    value-forwarded (one-round-stale table patched from the pending
    commit), and a post-loop drain applies the final round's routing, so
    grown trees, leaf ids and valid routings are bit-identical to the
    sequential schedule (tests/test_wave_pipeline.py pins this; the
    sequential path is the pin, config ``async_wave_pipeline=false``).
    """
    L = num_leaves
    L1 = max(L - 1, 1)
    K = max(1, min(wave_size, L1))
    B = num_bins
    W = -(-B // 32)
    use_mc = bool(np.asarray(meta.monotone_type).any())
    use_cat = bool(np.asarray(meta.is_categorical).any())
    use_inter = use_mc and monotone_mode == "intermediate"
    use_groups = interaction_groups is not None
    if use_inter:
        _mt = np.asarray(meta.monotone_type)
        inter_feats = [int(f) for f in np.where(_mt != 0)[0]]
        inter_types = [int(_mt[f]) for f in inter_feats]
    groups = (jnp.asarray(interaction_groups)
              if interaction_groups is not None else None)
    store = (_PackedStore if fused_bookkeeping else _FieldStore)(
        L, L1, W, use_mc, use_cat)
    use_fused = fused_round_fn is not None
    # single-pass wave round (ISSUE 15): a routing-capable fused_round_fn
    # (ops/wave_fused.make_fused_round — supports_route + the route_rows
    # valid-set router) folds the (S, N) partition into the kernel: the
    # binned rows are swept ONCE per round, the kernel emits the updated
    # leaf ids, and the valid sets ride the same decision stage.  The
    # feature-parallel trainer wrapper deliberately lacks the capability
    # (its shard sees only a feature slice), so it keeps the staged
    # partition below.
    use_fused_route = use_fused and getattr(fused_round_fn,
                                            "supports_route", False)
    if use_fused:
        from ..ops.wave_fused import unpack_children as _unpack_children

    # the default split accepts a per-child hist_scale (dequantize-aware
    # scan, ops/split.py), as do custom split_fns that declare
    # ``accepts_hist_scale = True`` (the sharded data-/voting-parallel
    # collectives, parallel/trainer.py — keeping the histogram integer
    # until AFTER their cross-chip reduce is the point of the int8sr
    # integer-domain collective); other custom split_fns (EFB bundle
    # decode, feature-parallel all_gather) keep their narrower signature
    # and get pre-dequantized histograms instead
    default_split = split_fn is None
    takes_scale = default_split or getattr(split_fn, "accepts_hist_scale",
                                           False)
    if split_fn is None:
        def split_fn(hist, parent, mask, key, uid, constraint, depth,
                     parent_output, hist_scale=None):
            rk = jax.random.fold_in(key, uid + 1_000_003 + params.extra_seed) \
                if params.extra_trees else None
            return find_best_split(hist, parent, meta, mask, params,
                                   constraint, depth, monotone_penalty,
                                   parent_output, rk, None,
                                   hist_scale=hist_scale)

    if sums_fn is None:
        def sums_fn(g3):
            return g3.sum(axis=0)

    if bins_of_fn is None:
        def bins_of_fn(binned, feat):
            return binned[feat]

    def allowed_features(used):
        return allowed_features_for(groups, used)

    def clamp_out(sums, constr, parent_out):
        # shared with the persistent wave-loop kernel (ops/split.py) —
        # both paths must run the same ops for the loop parity contract
        return child_leaf_output(sums, constr, parent_out, params,
                                 use_mc=use_mc)

    def grow(binned, g3, base_mask, key, cegb_used=None, valids=()):
        N = binned.shape[1]
        F = base_mask.shape[0]    # ORIGINAL feature count (binned may be
                                  # the narrower EFB bundle matrix)
        del cegb_used  # CEGB routes to the sequential grower (order-exact)

        # Slot buckets: the wave frontier RAMPS (1, 2, 4, ... splits per
        # round before reaching K), but a fixed-K round pays the full
        # 3*(K+1)-row MXU pass and the (K, N) partition regardless.  Rounds
        # with few splits therefore run a SLICED variant: the round's
        # n_split <= S splits are compacted to slots 0..n_split-1 and the
        # partition + histogram run at (S, N) / (S+1) slots — measured ~2x
        # cheaper at S=4 vs S=64 on the bench config (the remaining floor
        # is the slot-count-independent in-VMEM one-hot build).  Selection
        # is by the replicated n_split, so row shards stay in lockstep.
        slot_buckets = slot_buckets_for(K, N)
        # Quantized-pass eligibility (hist_dtype_deep="int8sr"): the
        # sustained largest bucket (the depth-adaptive deep gate) and the
        # 16-slot ramp bucket of a K>16 wave.  Root (the nslots=1 call
        # below) and the <=4-slot ramp buckets never quantize.
        quant_buckets = ()
        if hist_wave_quant_fn is not None and len(slot_buckets) > 1:
            quant_buckets = tuple(
                S for S in slot_buckets
                if (S == K and K >= 32) or (S == 16 and S < K))

        leaf_id0 = jnp.zeros(N, jnp.int32)
        hist0 = hist_wave_fn(binned, g3, leaf_id0, 1, deep=False)[0]
        # smaller-child + subtraction mode: build K child histograms per
        # round instead of 2K (halves the one-hot MXU pass and, in
        # data-parallel mode, the psum volume — the reference's
        # smaller-leaf trick, serial_tree_learner.cpp:274-314), deriving
        # the larger child from the per-leaf histogram state.  Skipped
        # when that state would exceed 512 MB (wide-F configs).
        use_sub = (L * int(np.prod(hist0.shape)) * 4) <= _SUB_STATE_CAP_BYTES
        # persistent multi-round wave loop (ROADMAP item 1): engage only
        # when the static plan says the whole frontier state fits VMEM
        # and every staged leg the loop cannot replicate in-kernel is
        # off.  The decision is trace-time — shapes and knobs only — so
        # the ineligible fallback is the unchanged single-round body.
        use_loop = False
        loop_plan = None
        if (fused_loop_fn is not None and use_fused_route
                and not (use_cat or use_mc or use_inter or use_groups)
                and feature_fraction_bynode >= 1.0):
            loop_plan = fused_loop_fn.plan(
                N=N, F=F, K=K, L=L, use_sub=use_sub,
                slot_buckets=slot_buckets, quant_buckets=quant_buckets)
            use_loop = bool(loop_plan["eligible"])
        # async wave pipelining: active whenever there is deferred work to
        # overlap — the per-leaf histogram-state scatter (use_sub) and/or
        # the valid-row routing.  With neither, the sequential body IS the
        # pipelined one (nothing to defer), so the pending carry is
        # skipped entirely and the paths are the same trace.  Loop mode
        # runs serialized (nothing defers across a kernel launch — the
        # in-loop rounds ARE the overlap); the pipelined staged path is
        # observably identical to the serialized one (value-forwarded
        # design, tests/test_wave_pipeline.py), so loop-vs-pipelined
        # parity follows transitively and is pinned under both flags.
        pipeline = (async_wave_pipeline and (use_sub or bool(valids))
                    and not use_loop)
        root_sum = sums_fn(g3)
        mask0 = _node_feature_mask(key, 0, base_mask, feature_fraction_bynode)
        mask0 = mask0 & allowed_features(jnp.zeros(F, bool))
        no_constr = jnp.asarray(NO_CONSTRAINT, jnp.float32)
        out0 = leaf_output(root_sum[0], root_sum[1], params)
        if params.path_smooth > 0:
            out0 = smooth_output(out0, root_sum[2], 0.0, params)
        res0 = split_fn(hist0, root_sum, mask0, key, 0, no_constr, 0, out0)

        # round-invariant work hoisted out of the while-loop body: with
        # per-node column sampling off and no interaction constraints the
        # children's feature mask is the same every round, and with no
        # monotone constraints every child's constraint is the NO_CONSTRAINT
        # constant — neither needs per-round gathers/scatters
        cmask_const = (jnp.broadcast_to(base_mask, (2 * K, F))
                       if feature_fraction_bynode >= 1.0 and not use_groups
                       else None)
        pconstr_const = (None if use_mc
                         else jnp.tile(no_constr, (K, 1)))
        cconstr_const = (None if use_mc
                         else jnp.tile(no_constr, (2 * K, 1)))

        # pipelined schedule: the pending no-op of round -1 — every index
        # is a drop slot and every routing slot is dead (leaf id L matches
        # no row), so the first body's drain is a bit-exact no-op
        pend0 = {}
        if pipeline:
            pend0 = dict(
                cidx=jnp.full(2 * K, L + 1, jnp.int32),
                feats=jnp.zeros(K, jnp.int32),
                thrs=jnp.zeros(K, jnp.int32),
                dls=jnp.zeros(K, bool),
                leafs=jnp.full(K, L, jnp.int32),
                nls=jnp.zeros(K, jnp.int32),
            )
            if use_sub:
                pend0["hist"] = jnp.zeros((2 * K,) + hist0.shape,
                                          jnp.float32)
            if use_cat:
                pend0["iscats"] = jnp.zeros(K, bool)
                pend0["bitsets"] = jnp.zeros((K, W), jnp.uint32)

        def route_pending(p, vb, vl):
            """Apply one pending round's split decisions to a valid set's
            leaf ids — the DEFERRED analog of the in-round ``go_left_s``
            valid routing, evaluated over the rank-order (K,) split
            metadata (dead slots carry leaf id L and match no row).  The
            per-row update terms are int32 — exact and summation-order
            free — so deferral is bit-identical to in-round routing.
            Under the routed fused kernel the drain rides the SAME
            decision stage as the train rows (``route_rows`` — the
            ISSUE 15 valid-set lane) instead of the staged gather
            chain; ``route_tile`` shares ``go_left_rule`` with the
            staged path, so the routing cannot diverge."""
            feats_k, thrs_k, dls_k = p["feats"], p["thrs"], p["dls"]
            leafs_k, nls_k = p["leafs"], p["nls"]
            if use_fused_route:   # fused gate excludes categorical sets
                return fused_round_fn.route_rows(
                    vb, vl, feats=feats_k, thrs=thrs_k, dls=dls_k,
                    leafs=leafs_k, nls=nls_k, num_leaves=L)
            mt_k = meta.missing_type[feats_k][:, None]
            bk = jax.vmap(lambda f: bins_of_fn(vb, f))(feats_k)
            bk = bk.astype(jnp.int32)
            g = go_left_rule(bk, thrs_k[:, None], dls_k[:, None], mt_k,
                             meta.nan_bin[feats_k][:, None],
                             meta.zero_bin[feats_k][:, None])
            if use_cat:
                word = jnp.zeros(bk.shape, jnp.uint32)
                for wv in range(W):
                    word = jnp.where((bk >> 5) == wv,
                                     p["bitsets"][:, wv][:, None], word)
                in_set = ((word >> (bk.astype(jnp.uint32) & 31)) & 1) == 1
                g = jnp.where(p["iscats"][:, None], in_set, g)
            mine = vl[None, :] == leafs_k[:, None]
            go_rv = mine & (~g)
            return vl + jnp.sum(
                jnp.where(go_rv, nls_k[:, None] - vl[None, :], 0), axis=0)

        st = WaveState(
            leaf_id=leaf_id0,
            valid_lids=tuple(jnp.zeros(v.shape[1], jnp.int32)
                             for v in valids),
            leaf_hist=(jnp.zeros((L,) + hist0.shape,
                                 jnp.float32).at[0].set(hist0)
                       if use_sub
                       else jnp.zeros((1,) + hist0.shape, jnp.float32)),
            store=store.init(res0, out0),
            leaf_box=(jnp.zeros((L, F, 2), jnp.int32)
                      .at[0, :, 1].set(meta.num_bins)
                      if use_inter else jnp.zeros((1, 1, 2), jnp.int32)),
            leaf_used=(jnp.zeros((L, F), bool) if use_groups
                       else jnp.zeros((1, 1), bool)),
            num_leaves=jnp.asarray(1, jnp.int32),
            done=jnp.asarray(L <= 1),
            pending=pend0,
        )

        kiota = jnp.arange(K, dtype=jnp.int32)

        def cond(st: WaveState):
            # max(best_gain) > 0 stops BEFORE a zero-split round: the old
            # `done | (n_split == 0)` exit ran one full (partition + hist)
            # pass just to discover nothing splits — a wasted round on
            # every gain-exhausted tree, and a trailing 0 the tree-replay
            # schedule (replay_wave_schedule) could not see.  A positive
            # frontier gain guarantees n_split >= 1 (the intermediate-
            # monotone deferral never clears the FIRST valid pick).
            return (~st.done) & (st.num_leaves < L) & \
                (jnp.max(store.gains(st.store)) > 0)

        def body(st: WaveState) -> WaveState:
            # ---- pipelined drain of the PREVIOUS round's deferred work ----
            # The leaf-histogram scatter and the valid-row routing of round
            # r-1 are issued HERE, inside round r's computation: both are
            # data-independent of this round's critical path (top-k →
            # partition decision → histogram MXU pass → split scan), so the
            # scheduler can overlap them with it — at the tail of body r-1
            # the while-loop barrier would have serialized them instead.
            # The subtraction below never waits on the drained scatter: its
            # parent rows are value-forwarded from the pending commit.
            if pipeline:
                p_hist = st.pending.get("hist")
                leaf_hist_in = (st.leaf_hist.at[st.pending["cidx"]]
                                .set(p_hist, mode="drop")
                                if use_sub else st.leaf_hist)
                vlids_in = tuple(
                    route_pending(st.pending, vb, vl)
                    for vb, vl in zip(valids, st.valid_lids))
            else:
                leaf_hist_in = st.leaf_hist
                vlids_in = st.valid_lids
            budget = L - st.num_leaves
            # routed fused rounds label the WHOLE round — the O(L) top-k
            # slot ranking, the in-kernel routing + histogram + scan and
            # the residue pick — as one `lgbm.fused_round` region, so
            # compile/cost/roofline telemetry (and the trace phase
            # profile's merged `phase_round_fused_ms` row) see a single
            # labeled executable instead of a partition/top-k residue
            fr_scope = (jax.named_scope("lgbm.fused_round") if use_fused
                        else contextlib.nullcontext())
            with fr_scope:
                vals, leafs = _topk_by_rank(store.gains(st.store),
                                            K)             # (K,)
            valid = (vals > 0) & (kiota < budget)
            if use_inter and K > 1:
                # soundness: two leaves ADJACENT along a monotone feature
                # must not split in the same round — each child would be
                # clamped against the neighbour's stale pre-round output
                # and monotonicity could break between the new children.
                # Defer the lower-ranked leaf of any adjacent pair to a
                # later round (it stays in the queue); the sequential
                # reference orders such splits implicitly.
                kb = st.leaf_box[leafs]                        # (K, F, 2)
                adj = jnp.zeros((K, K), bool)
                for _f, adj_up, adj_dn in _box_adjacency_per_feature(
                        kb[..., 0], kb[..., 1], inter_feats):
                    adj = adj | adj_up | adj_dn
                kept = valid
                for j in range(1, K):
                    clash = jnp.any(adj[j, :j] & kept[:j])
                    kept = kept.at[j].set(kept[j] & (~clash))
                valid = kept
            n_split = valid.sum()
            if _ROUND_PROBE is not None:   # bench round-schedule probe
                jax.debug.callback(_ROUND_PROBE, n_split)
            order = jnp.cumsum(valid.astype(jnp.int32)) - 1
            nodes = st.num_leaves - 1 + order                 # (K,) int32
            nls = st.num_leaves + order                       # new right leaves

            # one store read for every frontier field of the K split leaves
            # (the packed store turns 10+ per-field gathers into a single
            # (K, CF) table row gather)
            rd = store.read(st.store, leafs)
            feats, thrs, dls = rd["feats"], rd["thrs"], rd["dls"]
            iscats, bitsets = rd["iscats"], rd["bitsets"]     # (K,), (K, W)
            lsums, rsums = rd["lsums"], rd["rsums"]           # (K, 3)
            sm_left = lsums[:, 2] <= rsums[:, 2]              # (K,) smaller
            order_c = jnp.clip(order, 0, K - 1)
            # per-round rounding key for the quantized pass: the per-tree
            # key (unique per iteration x class) folded with the round's
            # leaf count, which strictly increases every round — the
            # (iteration, round) legs of the counter-based PRNG contract
            # (ops/quantize.py); the row block is the third leg, drawn
            # inside sr_quantize_g3
            rkey = (jax.random.fold_in(key, 8_000_011 + st.num_leaves)
                    if quant_buckets else None)

            # value-forwarded parent histogram rows, hoisted ahead of the
            # slot-bucket switch: the staged subtraction and the fused
            # kernel (which streams the parent stack as a kernel input)
            # must read the SAME forwarded values
            h_parent = None
            if use_sub and pipeline:
                # value forwarding: gather the parents from the ONE-
                # ROUND-STALE table and patch rows whose slot was
                # (over)written by the pending commit — identical
                # values to a post-scatter gather, but the subtracted
                # sibling's split scan starts without waiting for the
                # drained scatter (or the partition) to complete
                h_parent = st.leaf_hist[leafs]
                match = leafs[:, None] == st.pending["cidx"][None, :]
                hit = jnp.any(match, axis=1)
                src = jnp.argmax(match, axis=1)
                h_parent = jnp.where(hit[:, None, None, None],
                                     p_hist[src], h_parent)
            elif use_fused and use_sub:
                h_parent = leaf_hist_in[leafs]

            # ---- children metadata --------------------------------------
            # Hoisted ahead of the histogram dispatch (it depends only on
            # the store read): the fused kernel consumes the per-child
            # masks/constraints/outputs INSIDE its scan, so they must
            # exist before the slot-bucket switch; the staged split reads
            # the identical values after it.
            cleafs = jnp.stack([leafs, nls], axis=1).reshape(2 * K)
            csums = jnp.stack([lsums, rsums], axis=1).reshape(2 * K, 3)
            if use_inter:
                # fresh per-round constraints from leaf-region adjacency —
                # the outputs of neighbouring leaves may have changed since
                # this leaf's constraint was stored (the reference's
                # leaves_to_update_ propagation, monotone_constraints.hpp)
                constr_tab = intermediate_constraints(
                    st.leaf_box, store.leaf_out_full(st.store),
                    st.num_leaves, inter_feats, inter_types)
                pconstr = constr_tab[leafs]                   # (K, 2)
            elif use_mc:
                pconstr = rd["pconstr"]                       # (K, 2)
            else:
                pconstr = pconstr_const     # hoisted NO_CONSTRAINT rows
            pout = rd["pout"]                                 # (K,)
            out_l = jax.vmap(clamp_out)(lsums, pconstr, pout)
            out_r = jax.vmap(clamp_out)(rsums, pconstr, pout)
            if use_inter:
                # children bounded by the SIBLING's actual output
                # (UpdateConstraintsWithOutputs, monotone_constraints.hpp:154)
                mono = meta.monotone_type[feats]
                upd = (~iscats) & (mono != 0)
                max_l = jnp.where(upd & (mono > 0),
                                  jnp.minimum(pconstr[:, 1], out_r),
                                  pconstr[:, 1])
                min_l = jnp.where(upd & (mono < 0),
                                  jnp.maximum(pconstr[:, 0], out_r),
                                  pconstr[:, 0])
                max_r = jnp.where(upd & (mono < 0),
                                  jnp.minimum(pconstr[:, 1], out_l),
                                  pconstr[:, 1])
                min_r = jnp.where(upd & (mono > 0),
                                  jnp.maximum(pconstr[:, 0], out_l),
                                  pconstr[:, 0])
                constr_l = jnp.stack([min_l, max_l], axis=1)
                constr_r = jnp.stack([min_r, max_r], axis=1)
            elif use_mc:
                # BasicLeafConstraints::Update (monotone_constraints.hpp:99)
                mono = meta.monotone_type[feats]
                mid = 0.5 * (out_l + out_r)
                upd = (~iscats) & (mono != 0)
                max_l = jnp.where(upd & (mono > 0),
                                  jnp.minimum(pconstr[:, 1], mid), pconstr[:, 1])
                min_l = jnp.where(upd & (mono < 0),
                                  jnp.maximum(pconstr[:, 0], mid), pconstr[:, 0])
                max_r = jnp.where(upd & (mono < 0),
                                  jnp.minimum(pconstr[:, 1], mid), pconstr[:, 1])
                min_r = jnp.where(upd & (mono > 0),
                                  jnp.maximum(pconstr[:, 0], mid), pconstr[:, 0])
                constr_l = jnp.stack([min_l, max_l], axis=1)
                constr_r = jnp.stack([min_r, max_r], axis=1)
            if use_mc:
                cconstr = jnp.stack([constr_l, constr_r],
                                    axis=1).reshape(2 * K, 2)
            else:
                cconstr = cconstr_const     # hoisted NO_CONSTRAINT rows
            couts = jnp.stack([out_l, out_r], axis=1).reshape(2 * K)
            d = rd["pdepth"] + 1                              # (K,)
            cdepth = jnp.stack([d, d], axis=1).reshape(2 * K)
            depth_ok = (max_depth <= 0) | (cdepth < max_depth)

            cuids = jnp.stack([2 * nodes + 1, 2 * nodes + 2],
                              axis=1).reshape(2 * K)
            if use_groups:
                # branch-feature tracking feeds ONLY the interaction-
                # constraint mask — with no groups the whole block is
                # hoisted away (dead per-round one-hot + scatter)
                used_child = st.leaf_used[leafs] | jax.nn.one_hot(
                    feats, F, dtype=bool)                     # (K, F)
                cused = jnp.stack([used_child, used_child], axis=1) \
                    .reshape(2 * K, F)
                allow = jax.vmap(allowed_features)(cused)     # (2K, F)
            else:
                cused = allow = None
            if feature_fraction_bynode < 1.0:
                cmask = jax.vmap(
                    lambda u: _node_feature_mask(key, u, base_mask,
                                                 feature_fraction_bynode)
                )(cuids)
                if allow is not None:
                    cmask = cmask & allow
            elif allow is not None:
                cmask = jnp.broadcast_to(base_mask, (2 * K, F)) & allow
            else:
                cmask = cmask_const         # hoisted: same mask every round

            if use_inter:
                # child regions: a numerical split cuts the parent's box at
                # thr+1 along the split feature; categorical children keep
                # the parent box (conservative: more adjacency, never less)
                pbox = st.leaf_box[leafs]                     # (K, F, 2)
                kio = jnp.arange(K)
                cut = jnp.where(iscats, pbox[kio, feats, 1], thrs + 1)
                box_l = pbox.at[kio, feats, 1].set(cut)
                cut_lo = jnp.where(iscats, pbox[kio, feats, 0], thrs + 1)
                box_r = pbox.at[kio, feats, 0].set(cut_lo)

            # ---- decision + labeling + histogram, sliced to S slots -------
            # One vectorized (S, N) decision pass (the analog of K
            # DataPartition::Split scatters) + one (S+1)-slot histogram.
            # ``round_pass(S)`` is traced per slot bucket; the round's
            # n_split <= S splits are compacted to slots 0..n_split-1 via
            # ``order`` (cumsum of valid — dense even when the intermediate-
            # monotone deferral clears mid-prefix picks).
            def round_pass(S):
                sidx = jnp.where(valid, order_c, S)          # (K,) slot|drop

                def to_slot(v, fill):
                    base = jnp.full((S,) + v.shape[1:], fill, v.dtype)
                    return base.at[sidx].set(v, mode="drop")

                feats_s = to_slot(feats, 0)
                thrs_s = to_slot(thrs, 0)
                dls_s = to_slot(dls, False)
                # empty slots carry leaf id L: matches no row's leaf
                leafs_s = to_slot(leafs, L)
                nls_s = to_slot(nls, 0)
                sml_s = to_slot(sm_left, False)
                iscats_s = to_slot(iscats, False) if use_cat else None
                bitsets_s = to_slot(bitsets, 0) if use_cat else None

                def go_left_s(matrix):
                    """(S, rows) left-decision of this round's splits —
                    shared by the train partition and valid routing
                    (``go_left_rule`` is the single decision source,
                    shared with the fused kernel's routing stage)."""
                    mt_k = meta.missing_type[feats_s][:, None]
                    bk = jax.vmap(lambda f: bins_of_fn(matrix, f))(feats_s)
                    bk = bk.astype(jnp.int32)
                    g = go_left_rule(bk, thrs_s[:, None], dls_s[:, None],
                                     mt_k, meta.nan_bin[feats_s][:, None],
                                     meta.zero_bin[feats_s][:, None])
                    if use_cat:  # categorical bitset membership (bin-space)
                        word = jnp.zeros(bk.shape, jnp.uint32)
                        for wv in range(W):
                            word = jnp.where((bk >> 5) == wv,
                                             bitsets_s[:, wv][:, None], word)
                        in_set = ((word >> (bk.astype(jnp.uint32) & 31))
                                  & 1) == 1
                        g = jnp.where(iscats_s[:, None], in_set, g)
                    return g

                siota = jnp.arange(S, dtype=jnp.int32)
                if use_fused_route:
                    # ---- single-pass round (ISSUE 15): NO staged
                    # partition — the fused kernel evaluates the go-left
                    # decisions while sweeping the rows for the
                    # histograms and returns the updated leaf ids; valid
                    # sets ride the same decision stage (in-round here,
                    # via the drain above when pipelined)
                    label = leaf_id = None
                    vl_new = []
                    if not pipeline:
                        vl_new = [fused_round_fn.route_rows(
                            vb, vl, feats=feats_s, thrs=thrs_s,
                            dls=dls_s, leafs=leafs_s, nls=nls_s,
                            num_leaves=L)
                            for vb, vl in zip(valids, st.valid_lids)]
                else:
                    with jax.named_scope("lgbm.partition"):
                        gl = go_left_s(binned)                # (S, N)
                        mine = st.leaf_id[None, :] == leafs_s[:, None]
                        go_r = mine & (~gl)                   # disjoint rows
                        leaf_id = st.leaf_id + jnp.sum(
                            jnp.where(go_r,
                                      nls_s[:, None] - st.leaf_id[None, :],
                                      0), axis=0)
                        vl_new = []
                        if not pipeline:
                            # pipelined rounds defer valid routing to the
                            # next body's drain (route_pending) — off this
                            # round's critical path, bit-identical updates
                            for vb, vl in zip(valids, st.valid_lids):
                                gv = go_left_s(vb)
                                mine_v = vl[None, :] == leafs_s[:, None]
                                go_rv = mine_v & (~gv)
                                vl_new.append(vl + jnp.sum(
                                    jnp.where(go_rv,
                                              nls_s[:, None] - vl[None, :],
                                              0),
                                    axis=0))
                        if use_sub:
                            # label only the SMALLER child of each split
                            # (known up front from the recorded counts)
                            in_small = gl == sml_s[:, None]
                            label = jnp.sum(
                                jnp.where(mine & in_small,
                                          siota[:, None] - S, 0),
                                axis=0) + S
                        else:
                            slot2 = 2 * siota[:, None] \
                                + (~gl).astype(jnp.int32)
                            label = jnp.sum(
                                jnp.where(mine, slot2 - 2 * S, 0),
                                axis=0) + 2 * S

                # sustained rounds (the LARGEST bucket of a big wave) may
                # run the configured cheaper deep precision; ramp rounds
                # and the root pass always keep full precision.  With
                # bucketing off (small N) there ARE no separate ramp
                # variants — everything stays full precision
                deep = S == K and K >= 32 and len(slot_buckets) > 1
                nsl = S if use_sub else 2 * S
                if use_fused:
                    # ---- fused megakernel round: histogram + subtraction
                    # + split scan in ONE Pallas pass (ops/wave_fused.py).
                    # The per-child scan parameters are slot-compacted
                    # exactly like the slot arrays above (child 2s+lr of
                    # rank k with order_c[k] == s); dead ranks drop.
                    csidx = (2 * sidx[:, None]
                             + jnp.arange(2, dtype=jnp.int32)[None, :]
                             ).reshape(2 * K)

                    def to_cslot(v, fill):
                        base = jnp.full((2 * S,) + v.shape[1:], fill,
                                        v.dtype)
                        return base.at[csidx].set(v, mode="drop")

                    pr = None
                    if use_sub:
                        pr = jnp.zeros((S,) + h_parent.shape[1:],
                                       jnp.float32) \
                            .at[sidx].set(h_parent, mode="drop")
                    route = None
                    if use_fused_route:
                        route = dict(leaf_id=st.leaf_id, feats=feats_s,
                                     thrs=thrs_s, dls=dls_s,
                                     leafs=leafs_s, nls=nls_s,
                                     num_leaves=L)
                    fr_out = fused_round_fn(
                        binned, g3, label, S, deep=deep,
                        quant_key=rkey if S in quant_buckets else None,
                        scaled=bool(quant_buckets),
                        mask=to_cslot(cmask, False),
                        csums=to_cslot(csums, 1.0),
                        constr=to_cslot(cconstr, 0.0),
                        depth=to_cslot(cdepth, 1),
                        pout=to_cslot(couts, 0.0),
                        sml=sml_s if use_sub else None,
                        parent=pr, route=route)
                    if use_fused_route:
                        packed, h_sm, hsc, leaf_id = fr_out
                    else:
                        packed, h_sm, hsc = fr_out
                    if S < K:   # pad to the bucket-invariant width
                        packed = jnp.pad(packed,
                                         ((0, 2 * (K - S)), (0, 0)))
                    if not use_sub:
                        return (packed, leaf_id) + tuple(vl_new)
                    if S < K:
                        h_sm = jnp.pad(
                            h_sm, ((0, K - S),) + ((0, 0),) * 3)
                        hsc = jnp.concatenate(
                            [hsc, jnp.ones((K - S, 3), hsc.dtype)],
                            axis=0)
                    return (packed, h_sm, hsc, leaf_id) + tuple(vl_new)
                if S in quant_buckets:
                    # stochastic-rounded int8 pass: integer histogram +
                    # per-slot dequant scales, rounding stream keyed per
                    # (tree, round)
                    h, hsc = hist_wave_quant_fn(binned, g3, label, nsl,
                                                rkey)
                else:
                    h = hist_wave_fn(binned, g3, label, nsl, deep=deep)
                    hsc = jnp.ones((nsl, 3), jnp.float32)
                full = 2 * K if not use_sub else K
                if h.shape[0] < full:   # pad to the bucket-invariant width
                    h = jnp.concatenate(
                        [h, jnp.zeros((full - h.shape[0],) + h.shape[1:],
                                      h.dtype)], axis=0)
                    # padded slots dequantize as identity
                    hsc = jnp.concatenate(
                        [hsc, jnp.ones((full - hsc.shape[0], 3), hsc.dtype)],
                        axis=0)
                return (h, hsc, leaf_id) + tuple(vl_new)

            with (jax.named_scope("lgbm.fused_round") if use_fused
                  else contextlib.nullcontext()):
                if len(slot_buckets) > 1:
                    s_idx = jnp.zeros((), jnp.int32)
                    for S in slot_buckets[:-1]:
                        s_idx = s_idx + (n_split > S).astype(jnp.int32)
                    outs = lax.switch(
                        s_idx,
                        [lambda S=S: round_pass(S) for S in slot_buckets])
                else:
                    outs = round_pass(slot_buckets[0])
            if use_fused:
                if use_sub:
                    packed, h_slot, hscale, leaf_id = outs[:4]
                    tail = outs[4:]
                else:
                    packed, leaf_id = outs[:2]
                    h_slot = hscale = None
                    tail = outs[2:]
                new_vlids = vlids_in if pipeline else tuple(tail)
            else:
                h_slot, hscale, leaf_id = outs[0], outs[1], outs[2]
                new_vlids = vlids_in if pipeline else tuple(outs[3:])

            cscale = None                   # per-child dequant (quant rounds)
            if use_fused:
                # the kernel already scanned the children in VMEM; what
                # remains is the per-leaf table bookkeeping (subtraction
                # mode: the SAME subtract the kernel ran, recomputed on
                # the emitted smaller-child stack for the state scatter)
                # and the slot->rank gather of the packed SplitInfo
                if use_sub:
                    hist, h_left, h_right = subtract_child_hists(
                        h_slot, leaf_hist_in, leafs, order_c, sm_left,
                        slot_scale=hscale if quant_buckets else None,
                        h_parent=h_parent)
                ch_idx = jnp.stack([2 * order_c, 2 * order_c + 1],
                                   axis=1).reshape(2 * K)
                res = _unpack_children(packed[ch_idx], B)
            elif use_sub:
                # ---- smaller-child histograms + subtraction --------------
                # quant rounds fold the per-slot dequantization into the
                # subtraction pass (slot_scale); non-quant rounds carry
                # all-ones scales and skip the multiply entirely
                hist, h_left, h_right = subtract_child_hists(
                    h_slot, leaf_hist_in, leafs, order_c, sm_left,
                    slot_scale=hscale if quant_buckets else None,
                    h_parent=h_parent)
            else:
                ch_idx = jnp.stack([2 * order_c, 2 * order_c + 1],
                                   axis=1).reshape(2 * K)
                hist = h_slot[ch_idx]              # slot-order -> rank-order
                if quant_buckets:
                    # children come straight from the (possibly quantized)
                    # pass: hand the split scan the integer histograms +
                    # per-child scales (dequantize-aware scan) when the
                    # split accepts them, else dequantize here
                    cscale = hscale[ch_idx]                       # (2K, 3)
                    if not takes_scale:
                        hist = hist * cscale[:, None, None, :]
                        cscale = None

            # ---- batched split finding over the 2K children ---------------
            # (fused rounds already hold `res` — the kernel's packed
            # SplitInfo — and never route through split_fn)
            if use_fused:
                pass
            elif cscale is not None:
                # dequantize-aware scan: integer histograms + per-child
                # scales go straight into the gain cumsum (ops/split.py)
                res = jax.vmap(
                    lambda h, hs, p, m, u, c, dd, po: split_fn(
                        h, p, m, key, u, c, dd, po, hist_scale=hs)
                )(hist, cscale, csums, cmask, cuids, cconstr, cdepth, couts)
            else:
                res = jax.vmap(
                    lambda h, p, m, u, c, dd, po: split_fn(h, p, m, key, u,
                                                           c, dd, po)
                )(hist, csums, cmask, cuids, cconstr, cdepth, couts)
            cgain = jnp.where(depth_ok, res.gain, -jnp.inf)
            cvalid = jnp.stack([valid, valid], axis=1).reshape(2 * K)
            cidx = jnp.where(cvalid, cleafs, L + 1)           # drop slot

            # ---- tree assembly + frontier commit ------------------------
            # One store.write per round: the packed store coalesces the
            # whole commit into a 2K-row frontier-table scatter, a K-row
            # node-table scatter and a 2-column pointer fixup; the legacy
            # store reproduces the historical ~30 per-field scatters.
            nidx = jnp.where(valid, nodes, L1 + 1)
            lidx = jnp.where(valid, leafs, L + 1)
            nlidx = jnp.where(valid, nls, L + 1)
            p = rd["parent"]
            was_left = rd["was_left"]
            fix_l = jnp.where(valid & (p >= 0) & was_left,
                              jnp.maximum(p, 0), L1 + 1)
            fix_r = jnp.where(valid & (p >= 0) & (~was_left),
                              jnp.maximum(p, 0), L1 + 1)
            psum_k = lsums + rsums                            # parent sums
            new_store = store.write(st.store, dict(
                res=res, cgain=cgain, cidx=cidx, nidx=nidx,
                lidx=lidx, nlidx=nlidx, fix_l=fix_l, fix_r=fix_r,
                leafs=leafs, nls=nls,
                feats=feats, thrs=thrs, dls=dls,
                iscats=iscats, bitsets=bitsets,
                mtypes=meta.missing_type[feats],
                vals=vals, pout=pout, psum=psum_k,
                lsums=lsums, rsums=rsums, csums=csums,
                out_l=out_l, out_r=out_r, couts=couts,
                cdepth=cdepth, cconstr=cconstr,
                num_leaves_new=st.num_leaves + n_split,
            ))

            if pipeline:
                # this round's commits become the NEXT round's pending:
                # the (already drained-in) table rides forward unchanged
                # and the scatter + valid routing defer one round
                leaf_hist = leaf_hist_in
                new_pending = dict(
                    cidx=cidx,
                    feats=feats, thrs=thrs, dls=dls,
                    leafs=jnp.where(valid, leafs, L), nls=nls,
                )
                if use_sub:
                    new_pending["hist"] = hist
                if use_cat:
                    new_pending["iscats"] = iscats
                    new_pending["bitsets"] = bitsets
            elif use_sub:
                # packed: ONE interleaved scatter at cidx (hist is already
                # the rank-interleaved (2K, ...) child stack); legacy: the
                # historical two half-scatters
                leaf_hist = (
                    st.leaf_hist.at[cidx].set(hist, mode="drop")
                    if store.fused else
                    st.leaf_hist.at[lidx].set(h_left, mode="drop")
                    .at[nlidx].set(h_right, mode="drop"))
                new_pending = st.pending
            else:
                leaf_hist = st.leaf_hist
                new_pending = st.pending

            return WaveState(
                leaf_id=leaf_id,
                valid_lids=new_vlids,
                leaf_hist=leaf_hist,
                store=new_store,
                leaf_box=(st.leaf_box.at[lidx].set(box_l, mode="drop")
                          .at[nlidx].set(box_r, mode="drop")
                          if use_inter else st.leaf_box),
                leaf_used=(st.leaf_used.at[cidx].set(cused, mode="drop")
                           if use_groups else st.leaf_used),
                num_leaves=st.num_leaves + n_split,
                done=st.done | (n_split == 0),
                pending=new_pending,
            )

        R_loop = loop_plan["rounds"] if use_loop else 0

        def body_loop(st: WaveState) -> WaveState:
            # ---- persistent multi-round segment (ROADMAP item 1) ----
            # ONE kernel launch runs R_loop consecutive rounds with the
            # frontier table, histogram pool and row→leaf labels resident
            # in VMEM (ops/wave_fused.make_fused_wave_loop); the staged
            # bookkeeping below REPLAYS the rounds from the emitted
            # per-round packed SplitInfo — the same store.write/
            # route_rows code path as the single-round body, so trees,
            # stores and valid routings are bit-identical.  Rounds past
            # an exhausted frontier are bit-exact no-ops (every scatter
            # drops, the leaf count stays put) both in-kernel and here.
            rows_all = store.read(st.store,
                                  jnp.arange(L, dtype=jnp.int32))
            ft12 = jnp.concatenate([
                store.gains(st.store)[:, None],
                rows_all["feats"].astype(jnp.float32)[:, None],
                rows_all["thrs"].astype(jnp.float32)[:, None],
                rows_all["dls"].astype(jnp.float32)[:, None],
                rows_all["lsums"], rows_all["rsums"],
                rows_all["pout"][:, None],
                rows_all["pdepth"].astype(jnp.float32)[:, None]], axis=1)
            with jax.named_scope("lgbm.fused_loop"):
                packed_R, leaf_id_new, pool_new = fused_loop_fn(
                    binned, g3, st.leaf_id, ft12, st.num_leaves, key,
                    K=K, slot_buckets=slot_buckets,
                    quant_buckets=quant_buckets, max_depth=max_depth,
                    base_mask=base_mask,
                    pool=(st.leaf_hist if use_sub else None))
            store_s = st.store
            nl_s = st.num_leaves
            vlids_s = st.valid_lids
            done_s = st.done
            for rr in range(R_loop):
                vals, leafs = _topk_by_rank(store.gains(store_s), K)
                budget = L - nl_s
                valid = (vals > 0) & (kiota < budget)
                n_split = valid.sum()
                if _ROUND_PROBE is not None:   # bench round-schedule probe
                    jax.debug.callback(_ROUND_PROBE, n_split)
                order = jnp.cumsum(valid.astype(jnp.int32)) - 1
                nodes = nl_s - 1 + order
                nls = nl_s + order
                rd = store.read(store_s, leafs)
                feats, thrs, dls = rd["feats"], rd["thrs"], rd["dls"]
                iscats, bitsets = rd["iscats"], rd["bitsets"]
                lsums, rsums = rd["lsums"], rd["rsums"]
                order_c = jnp.clip(order, 0, K - 1)
                cleafs = jnp.stack([leafs, nls], axis=1).reshape(2 * K)
                csums = jnp.stack([lsums, rsums],
                                  axis=1).reshape(2 * K, 3)
                pout = rd["pout"]
                out_l = jax.vmap(clamp_out)(lsums, pconstr_const, pout)
                out_r = jax.vmap(clamp_out)(rsums, pconstr_const, pout)
                couts = jnp.stack([out_l, out_r], axis=1).reshape(2 * K)
                d = rd["pdepth"] + 1
                cdepth = jnp.stack([d, d], axis=1).reshape(2 * K)
                depth_ok = (max_depth <= 0) | (cdepth < max_depth)
                ch_idx = jnp.stack([2 * order_c, 2 * order_c + 1],
                                   axis=1).reshape(2 * K)
                res = _unpack_children(packed_R[rr][ch_idx], B)
                cgain = jnp.where(depth_ok, res.gain, -jnp.inf)
                cvalid = jnp.stack([valid, valid], axis=1).reshape(2 * K)
                cidx = jnp.where(cvalid, cleafs, L + 1)
                nidx = jnp.where(valid, nodes, L1 + 1)
                lidx = jnp.where(valid, leafs, L + 1)
                nlidx = jnp.where(valid, nls, L + 1)
                p = rd["parent"]
                was_left = rd["was_left"]
                fix_l = jnp.where(valid & (p >= 0) & was_left,
                                  jnp.maximum(p, 0), L1 + 1)
                fix_r = jnp.where(valid & (p >= 0) & (~was_left),
                                  jnp.maximum(p, 0), L1 + 1)
                psum_k = lsums + rsums
                store_s = store.write(store_s, dict(
                    res=res, cgain=cgain, cidx=cidx, nidx=nidx,
                    lidx=lidx, nlidx=nlidx, fix_l=fix_l, fix_r=fix_r,
                    leafs=leafs, nls=nls,
                    feats=feats, thrs=thrs, dls=dls,
                    iscats=iscats, bitsets=bitsets,
                    mtypes=meta.missing_type[feats],
                    vals=vals, pout=pout, psum=psum_k,
                    lsums=lsums, rsums=rsums, csums=csums,
                    out_l=out_l, out_r=out_r, couts=couts,
                    cdepth=cdepth, cconstr=cconstr_const,
                    num_leaves_new=nl_s + n_split,
                ))
                if valids:
                    # per-replayed-round valid routing over the rank
                    # arrays (dead ranks carry leaf id L, matching no
                    # row) — the same route_rows decision stage as
                    # route_pending's fused leg, bit-identical to the
                    # in-round slot routing
                    vlids_s = tuple(fused_round_fn.route_rows(
                        vb, vl, feats=feats, thrs=thrs, dls=dls,
                        leafs=jnp.where(valid, leafs, L), nls=nls,
                        num_leaves=L)
                        for vb, vl in zip(valids, vlids_s))
                done_s = done_s | (n_split == 0)
                nl_s = nl_s + n_split

            return WaveState(
                leaf_id=leaf_id_new,
                valid_lids=vlids_s,
                leaf_hist=(pool_new if use_sub else st.leaf_hist),
                store=store_s,
                leaf_box=st.leaf_box,
                leaf_used=st.leaf_used,
                num_leaves=nl_s,
                done=done_s,
                pending=st.pending,
            )

        if L > 1:
            st = lax.while_loop(cond, body_loop if use_loop else body, st)
        tree = store.finalize(st.store, st.num_leaves)
        vlids_out = st.valid_lids
        if pipeline and valids:
            # drain: the final round's valid routing is still pending when
            # the loop exits (the histogram-state scatter is dead — the
            # table is intra-growth state).  After this the returned
            # routing is exactly the sequential schedule's, so checkpoint
            # and snapshot boundaries see fully-applied state and PR 6's
            # kill-at-k bit-exact resume guarantee is unchanged.
            vlids_out = tuple(route_pending(st.pending, vb, vl)
                              for vb, vl in zip(valids, vlids_out))
        if valids:
            return tree, st.leaf_id, root_sum, vlids_out
        return tree, st.leaf_id, root_sum

    grow._supports_valids = True
    return grow
