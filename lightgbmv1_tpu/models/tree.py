"""Tree model arrays and vectorized prediction.

TPU-native re-design of the reference flat-array tree
(reference: ``class Tree``, include/LightGBM/tree.h:25-602, src/io/tree.cpp).

Node encoding follows the reference exactly so the v3 model-text format
round-trips: internal nodes are numbered in split order; ``left_child`` /
``right_child`` hold either an internal node index (>= 0) or ``~leaf_index``
(< 0).  Prediction is a fully vectorized root-to-leaf walk: every row carries
its current node index and a ``lax.while_loop`` advances all rows together
(the reference's per-row ``Tree::Predict`` walk, tree.h:132, becomes a
gather + select per level).

Deployment-scale batched inference lives in ``models/predict.py`` (the
depth-stepped all-trees walk, prebinned serving codes, predictor cache);
this module keeps the single-tree training-time walks, the stacked-scan
parity pin, and the shared host-side structure validators.
"""

from __future__ import annotations

import os
from typing import List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..io.binning import K_ZERO_THRESHOLD, MISSING_NAN, MISSING_NONE, MISSING_ZERO


class TreeArrays(NamedTuple):
    """One tree (or a stack of trees when arrays carry a leading T axis)."""

    num_leaves: jax.Array       # () int32 — actual leaves (arrays are padded)
    split_feature: jax.Array    # (L-1,) int32
    threshold_bin: jax.Array    # (L-1,) int32
    threshold: jax.Array        # (L-1,) float32 — real-valued threshold
    default_left: jax.Array     # (L-1,) bool
    missing_type: jax.Array     # (L-1,) int32 — copied from split feature meta
    left_child: jax.Array       # (L-1,) int32 (>=0 node, <0 is ~leaf)
    right_child: jax.Array      # (L-1,) int32
    split_gain: jax.Array       # (L-1,) float32
    internal_value: jax.Array   # (L-1,) float32
    internal_weight: jax.Array  # (L-1,) float32
    internal_count: jax.Array   # (L-1,) float32
    leaf_value: jax.Array       # (L,) float32
    leaf_weight: jax.Array      # (L,) float32
    leaf_count: jax.Array       # (L,) float32
    leaf_parent: jax.Array      # (L,) int32
    is_cat: jax.Array           # (L-1,) bool — categorical (bitset) split
    cat_bitset: jax.Array       # (L-1, W) uint32 — bin-space membership
                                # (reference: cat_threshold_inner_, tree.h:427)


# Debug-mode bounds contract for leaf_lookup (set LGBM_TPU_DEBUG_BOUNDS=1
# or flip this flag in tests): out-of-range leaf ids poison their rows
# with NaN instead of silently contributing 0.0, so a caller relying on
# the gather's clamp semantics fails loudly instead of training on wrong
# scores.  Off by default — the where() adds a pass over the rows.
DEBUG_BOUNDS = bool(int(os.environ.get("LGBM_TPU_DEBUG_BOUNDS", "0")))


def leaf_lookup(table: jax.Array, leaf_id: jax.Array) -> jax.Array:
    """``table[leaf_id]`` without a device gather.

    PRECONDITION: every ``leaf_id`` must be in ``[0, len(table))``.  The
    XLA gather this replaces CLAMPS out-of-bounds indices to the edge
    entry; the broadcast-compare below instead contributes **0.0** for
    any out-of-range id — a silent semantic change for a caller that
    relied on the clamp.  All in-tree call sites pass partition-produced
    leaf ids, which are in-range by construction; new callers must
    guarantee the same (enable ``DEBUG_BOUNDS`` to get NaN poisoning on
    violations instead of silent zeros).

    TPU gathers run at ~1 element per several cycles (7.8 ms for 1M rows
    from a 255-entry table, tools/microbench_gather.py) while a
    broadcast-compare select-reduce streams the same lookup in ~0.8 ms
    and is EXACT — each row reduces exactly one nonzero, so there is no
    summation error.  Falls back to the native gather for wide tables
    where the O(rows·L) compare loses.  This is the score-application
    analog of the reference ScoreUpdater's per-leaf AddScore
    (src/boosting/score_updater.hpp), reformulated for the VPU."""
    L = table.shape[0]
    lid = leaf_id.astype(jnp.int32)
    if L > 1024:
        out = table[leaf_id]
    else:
        iota = jnp.arange(L, dtype=jnp.int32)
        eq = lid[:, None] == iota[None, :]
        # Each element of the result is value-equal to table[leaf_id], but
        # consumers may see 1-ulp drift vs the gather formulation: XLA is
        # free to reassociate a producer's scale factor across the reduce
        # and fma-fuse into a consumer add (one rounding instead of two).
        # Paths with a PINNED bit-parity contract (the wave grower's
        # valid-score routing vs the tree walk) therefore keep the native
        # gather — valid sets are small; this formulation is for the big
        # train-row tables.
        out = jnp.sum(jnp.where(eq, table[None, :], 0), axis=1)
    if DEBUG_BOUNDS:
        out = jnp.where((lid >= 0) & (lid < L), out, jnp.nan)
    return out


def empty_tree(max_leaves: int, cat_words: int = 1) -> TreeArrays:
    L = max_leaves
    L1 = max(L - 1, 1)
    return TreeArrays(
        num_leaves=jnp.asarray(1, jnp.int32),
        split_feature=jnp.zeros(L1, jnp.int32),
        threshold_bin=jnp.zeros(L1, jnp.int32),
        threshold=jnp.zeros(L1, jnp.float32),
        default_left=jnp.zeros(L1, bool),
        missing_type=jnp.zeros(L1, jnp.int32),
        left_child=jnp.full(L1, -1, jnp.int32),
        right_child=jnp.full(L1, -2, jnp.int32),
        split_gain=jnp.zeros(L1, jnp.float32),
        internal_value=jnp.zeros(L1, jnp.float32),
        internal_weight=jnp.zeros(L1, jnp.float32),
        internal_count=jnp.zeros(L1, jnp.float32),
        leaf_value=jnp.zeros(L, jnp.float32),
        leaf_weight=jnp.zeros(L, jnp.float32),
        leaf_count=jnp.zeros(L, jnp.float32),
        leaf_parent=jnp.full(L, -1, jnp.int32),
        is_cat=jnp.zeros(L1, bool),
        cat_bitset=jnp.zeros((L1, cat_words), jnp.uint32),
    )


# ---------------------------------------------------------------------------
# Binned prediction (training-time: validation data shares the training bins)
# ---------------------------------------------------------------------------


def tree_leaf_index_binned(
    tree: TreeArrays,
    binned: jax.Array,        # (F, N) bins, (BF, N) EFB bundles, or
                              # (ceil(F/2), N) 4-bit packed bytes
    nan_bins: jax.Array,      # (F,) int32
    missing_types: jax.Array,  # (F,) int32
    bundle=None,              # io/bundle.py BundleArrays when EFB applied
    packed: bool = False,     # 4-bit packed bins (two features per byte)
    zero_bins=None,           # (F,) int32 — zero-as-missing routing
) -> jax.Array:               # (N,) int32 leaf index per row
    N = binned.shape[1]
    # Walks are BOUNDED by the node count: an acyclic root-to-leaf path
    # visits each internal node at most once, so `n_nodes` steps always
    # suffice; a malformed/cyclic model (caught at model-text load by
    # validate_host_tree, but constructible via the array API) terminates
    # instead of hanging the predictor.
    max_steps = int(tree.split_feature.shape[0]) + 1

    def cond(state):
        node, it = state
        return jnp.any(node >= 0) & (it < max_steps)

    def body(state):
        node, it = state
        active = node >= 0
        nd = jnp.maximum(node, 0)
        f = tree.split_feature[nd]
        if bundle is not None:
            from ..io.bundle import bundle_bins_of_rows

            b = bundle_bins_of_rows(binned, f, bundle)
        elif packed:
            from ..ops.hist_pallas import packed_bins_of_rows

            b = packed_bins_of_rows(binned, f)
        else:
            b = jnp.take_along_axis(binned, f[None, :], axis=0)[0]
        t = tree.threshold_bin[nd]
        dl = tree.default_left[nd]
        is_na = (missing_types[f] == MISSING_NAN) & (b == nan_bins[f])
        if zero_bins is not None:
            # zero-as-missing rows follow the node's default direction
            # (reference NumericalDecision MissingType::Zero, tree.h:~430;
            # training-side the zero mass rides the scan direction)
            is_na = is_na | ((missing_types[f] == MISSING_ZERO)
                             & (b == zero_bins[f]))
        go_left = jnp.where(is_na, dl, b <= t)
        # categorical: bitset membership (reference CategoricalDecisionInner,
        # tree.h:322-335); the other/unseen bin is never in the set => right
        W = tree.cat_bitset.shape[-1]
        bi = b.astype(jnp.int32)
        word = tree.cat_bitset.reshape(-1)[nd * W + (bi >> 5)]
        in_set = ((word >> (bi.astype(jnp.uint32) & 31)) & 1) == 1
        go_left = jnp.where(tree.is_cat[nd], in_set, go_left)
        nxt = jnp.where(go_left, tree.left_child[nd], tree.right_child[nd])
        node = jnp.where(active, nxt, node)
        return node, it + 1

    node0 = jnp.where(tree.num_leaves > 1,
                      jnp.zeros(N, jnp.int32),
                      jnp.full(N, -1, jnp.int32))
    node, _ = lax.while_loop(cond, body, (node0, jnp.asarray(0, jnp.int32)))
    return -node - 1   # ~node


def leaf_path_features(tree: TreeArrays, num_features: int) -> jax.Array:
    """(L, F) bool — the features split on along each leaf's root path
    (the reference's per-leaf branch features).  Used to mark rows for
    cegb_penalty_feature_lazy: a row 'uses' exactly the features on its
    leaf's path (cost_effective_gradient_boosting.hpp:110-121 marks the
    split leaf's rows at every applied split — the union over the tree is
    precisely the path features of each row's final leaf)."""
    L1 = tree.left_child.shape[0]
    L = tree.leaf_parent.shape[0]
    nidx = jnp.arange(L1, dtype=jnp.int32)
    par = jnp.full(L1, -1, jnp.int32)
    par = par.at[jnp.where(tree.left_child >= 0, tree.left_child,
                           L1 + 1)].set(nidx, mode="drop")
    par = par.at[jnp.where(tree.right_child >= 0, tree.right_child,
                           L1 + 1)].set(nidx, mode="drop")

    def body(_, carry):
        node, feats = carry
        active = node >= 0
        nd = jnp.maximum(node, 0)
        f = tree.split_feature[nd]
        feats = feats | (jax.nn.one_hot(f, num_features, dtype=bool)
                         & active[:, None])
        node = jnp.where(active, par[nd], -1)
        return node, feats

    node0 = tree.leaf_parent
    feats0 = jnp.zeros((L, num_features), bool)
    _, feats = lax.fori_loop(0, max(L1, 1), body, (node0, feats0))
    return feats


def tree_predict_binned(tree, binned, nan_bins, missing_types, bundle=None,
                        packed: bool = False, zero_bins=None):
    leaf = tree_leaf_index_binned(tree, binned, nan_bins, missing_types,
                                  bundle, packed, zero_bins)
    return tree.leaf_value[leaf]


# ---------------------------------------------------------------------------
# Raw-feature prediction (deployment path, reference Tree::Predict)
# ---------------------------------------------------------------------------


def tree_predict_raw(tree: TreeArrays, X: jax.Array) -> jax.Array:
    """X: (N, F) float; NaN = missing. Mirrors Tree::NumericalDecision
    (reference include/LightGBM/tree.h:~430) including missing-type handling.

    Categorical (bitset) nodes are not supported on this device path — the
    deployment predictor for categorical models is the host ``HostTree``
    walk (Booster.predict) or the binned path; raw categorical decisions
    need the raw->bin category dictionary, which lives host-side."""
    N = X.shape[0]
    # bounded like tree_leaf_index_binned: a cyclic child graph must
    # terminate (garbage scores beat a hung predictor; load-time
    # validation is the correctness gate)
    max_steps = int(tree.split_feature.shape[0]) + 1

    def cond(state):
        node, it = state
        return jnp.any(node >= 0) & (it < max_steps)

    def body(state):
        node, it = state
        active = node >= 0
        nd = jnp.maximum(node, 0)
        f = tree.split_feature[nd]
        v = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
        t = tree.threshold[nd]
        dl = tree.default_left[nd]
        mtype = tree.missing_type[nd]
        is_nan = jnp.isnan(v)
        v0 = jnp.where(is_nan, 0.0, v)
        is_missing = jnp.where(
            mtype == MISSING_NAN,
            is_nan,
            jnp.where(mtype == MISSING_ZERO,
                      is_nan | (jnp.abs(v0) <= K_ZERO_THRESHOLD), False),
        )
        go_left = jnp.where(is_missing, dl, v0 <= t)
        nxt = jnp.where(go_left, tree.left_child[nd], tree.right_child[nd])
        return jnp.where(active, nxt, node), it + 1

    node0 = jnp.where(tree.num_leaves > 1,
                      jnp.zeros(N, jnp.int32),
                      jnp.full(N, -1, jnp.int32))
    node, _ = lax.while_loop(cond, body, (node0, jnp.asarray(0, jnp.int32)))
    return tree.leaf_value[-node - 1]


def tree_used_features(tree: TreeArrays, num_features: int) -> jax.Array:
    """(F,) bool — features used by this tree's valid internal nodes
    (CEGB model-level used-feature tracking, the analog of
    is_feature_used_in_split_ in cost_effective_gradient_boosting.hpp)."""
    n_nodes = tree.split_feature.shape[0]
    valid = jnp.arange(n_nodes) < (tree.num_leaves - 1)
    oh = jax.nn.one_hot(tree.split_feature, num_features, dtype=bool)
    return jnp.any(oh & valid[:, None], axis=0)


def stack_trees(trees: List[TreeArrays]) -> TreeArrays:
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def host_trees_to_stacked(trees, num_leaves: int = 0) -> TreeArrays:
    """Pad HostTrees (ragged per-tree arrays, REAL thresholds filled) back
    to a fixed-size stacked ``TreeArrays`` for the device batch walk
    (``ensemble_predict_raw``).

    The training-time ``_device_trees`` carry bin-space thresholds only
    (``threshold`` is zeros until ``_fill_real_thresholds`` runs on the
    host copy), so deployment prediction on RAW features must route
    through the host trees — this is the bridge back to the device."""
    L = num_leaves or max(max(t.num_leaves, 2) for t in trees)
    L1 = max(L - 1, 1)
    W = max((t.cat_bitset.shape[1] if t.cat_bitset.ndim == 2
             and t.cat_bitset.shape[0] else 1) for t in trees)

    def pad(a, n, fill, dtype):
        out = np.full(n, fill, dtype)
        out[: len(a)] = a
        return out

    def pad2(a, n, w):
        out = np.zeros((n, w), np.uint32)
        if a.ndim == 2 and a.shape[0]:
            out[: a.shape[0], : a.shape[1]] = a
        return out

    arrs = []
    for t in trees:
        arrs.append(TreeArrays(
            num_leaves=np.int32(t.num_leaves),
            split_feature=pad(t.split_feature, L1, 0, np.int32),
            threshold_bin=pad(t.threshold_bin, L1, 0, np.int32),
            threshold=pad(t.threshold, L1, 0.0, np.float32),
            default_left=pad(t.default_left, L1, False, bool),
            missing_type=pad(t.missing_type, L1, 0, np.int32),
            left_child=pad(t.left_child, L1, -1, np.int32),
            right_child=pad(t.right_child, L1, -2, np.int32),
            split_gain=pad(t.split_gain, L1, 0.0, np.float32),
            internal_value=pad(t.internal_value, L1, 0.0, np.float32),
            internal_weight=pad(t.internal_weight, L1, 0.0, np.float32),
            internal_count=pad(t.internal_count, L1, 0, np.float32),
            leaf_value=pad(t.leaf_value, L, 0.0, np.float32),
            leaf_weight=pad(t.leaf_weight, L, 0.0, np.float32),
            leaf_count=pad(t.leaf_count, L, 0, np.float32),
            leaf_parent=pad(t.leaf_parent, L, -1, np.int32),
            is_cat=pad(t.is_cat, L1, False, bool),
            cat_bitset=pad2(t.cat_bitset, L1, W),
        ))
    return stack_trees([jax.tree_util.tree_map(jnp.asarray, a)
                        for a in arrs])


def ensemble_predict_raw(stacked: TreeArrays, X: jax.Array) -> jax.Array:
    """Sum of all stacked trees' raw predictions for each row.

    PARITY PIN: the sequential per-tree scan walk (one data-dependent
    while-loop per tree).  Deployment prediction routes through the
    depth-stepped all-trees walk (models/predict.serving_leaf_raw /
    serving_leaf_binned); this path is kept as the bit-parity reference
    and is reachable via ``predict_method=scan``."""

    def step(acc, tree):
        return acc + tree_predict_raw(tree, X), None

    out, _ = lax.scan(step, jnp.zeros(X.shape[0], jnp.float32), stacked)
    return out


def leaves_to_scores(leaf_value: jax.Array, leaf: jax.Array,
                     K: int) -> jax.Array:
    """(N, T) leaf indices + (T, L) stacked leaf values -> (N, K) raw
    scores, class k summing trees ``k, k+K, k+2K, ...`` (iteration-major
    tree order, reference GBDT::PredictRaw)."""
    N, T = leaf.shape
    ti = jnp.arange(T, dtype=jnp.int32)[None, :]
    vals = leaf_value[ti, leaf]                            # (N, T)
    return vals.reshape(N, T // K, K).sum(axis=1)


def pad_tree_axis(tables, t_pad: int):
    """Zero-pad every stacked (T, ...) table along the TREE axis to
    ``t_pad`` trees — the fused serving kernel's tile slicing
    (ops/predict_pallas.serving_fused_pallas) needs the tree axis to be
    a multiple of the planner's tree tile.  A zero-padded tree has
    ``num_leaves == 0``, so the walks park it on leaf 0 whose value is
    0.0: scores are unchanged and leaf-mode callers slice the pad away.
    Works on any NamedTuple of stacked arrays whose leading axis is T
    (ServingArrays, TreeArrays)."""
    T = int(tables.num_leaves.shape[0])
    if t_pad < T:
        raise ValueError(f"t_pad={t_pad} < T={T}")
    if t_pad == T:
        return tables
    pad = t_pad - T
    return type(tables)(*(
        jnp.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
        for a in tables))


def validate_host_tree(t, index: int = -1) -> None:
    """Child-pointer structural validation (cycle / out-of-range /
    reconvergence / unreachable-leaf detection).  A malformed model file
    previously HUNG the bounded-by-``any(active)`` while-loop walks; now
    load fails loudly here and the device walks are step-bounded as
    defense in depth.  Raises ``ValueError``."""
    n = int(t.num_leaves)
    where = f"tree {index}" if index >= 0 else "tree"
    if n <= 1:
        return
    n_nodes = n - 1
    lc = np.asarray(t.left_child)
    rc = np.asarray(t.right_child)
    if len(lc) < n_nodes or len(rc) < n_nodes:
        raise ValueError(f"{where}: child arrays shorter than num_leaves-1")
    seen = np.zeros(n_nodes, bool)
    seen_leaf = np.zeros(n, bool)
    seen[0] = True
    stack = [0]
    while stack:
        nd = stack.pop()
        for c in (int(lc[nd]), int(rc[nd])):
            if c >= 0:
                if c >= n_nodes:
                    raise ValueError(
                        f"{where}: child index {c} out of range "
                        f"(num_leaves={n})")
                if seen[c]:
                    raise ValueError(
                        f"{where}: node {c} reached twice — cyclic or "
                        "reconvergent child pointers")
                seen[c] = True
                stack.append(c)
            else:
                leaf = -c - 1
                if leaf >= n:
                    raise ValueError(
                        f"{where}: leaf index {leaf} out of range "
                        f"(num_leaves={n})")
                if seen_leaf[leaf]:
                    raise ValueError(
                        f"{where}: leaf {leaf} reached twice — malformed "
                        "child pointers")
                seen_leaf[leaf] = True
    if not seen.all():
        raise ValueError(f"{where}: unreachable internal nodes "
                         f"{np.flatnonzero(~seen).tolist()}")
    if not seen_leaf.all():
        raise ValueError(f"{where}: unreachable leaves "
                         f"{np.flatnonzero(~seen_leaf).tolist()}")


def host_tree_depth(t) -> int:
    """Max root-to-leaf decision count (edges).  Assumes a validated
    tree; guards the level walk by the node count regardless."""
    n = int(t.num_leaves)
    if n <= 1:
        return 0
    n_nodes = n - 1
    lc = np.asarray(t.left_child)
    rc = np.asarray(t.right_child)
    depth = 0
    frontier = [0]
    while frontier and depth <= n_nodes:
        depth += 1
        frontier = [c for nd in frontier for c in (int(lc[nd]), int(rc[nd]))
                    if c >= 0]
    if frontier:
        raise ValueError("host_tree_depth: path longer than the node "
                         "count — cyclic child pointers")
    return depth


# ---------------------------------------------------------------------------
# Host-side (numpy) tree — exact mirror used by the text model format/CLI
# ---------------------------------------------------------------------------


class HostTree:
    """Numpy copy of one tree; the object serialized to/from model text."""

    FIELDS = [
        "split_feature", "threshold_bin", "threshold", "default_left",
        "missing_type", "left_child", "right_child", "split_gain",
        "internal_value", "internal_weight", "internal_count",
        "leaf_value", "leaf_weight", "leaf_count", "leaf_parent",
    ]

    def __init__(self, arrays: TreeArrays, shrinkage: float = 1.0):
        self.num_leaves = int(arrays.num_leaves)
        n_nodes = max(self.num_leaves - 1, 0)
        as_np = lambda a: np.asarray(a)
        self.split_feature = as_np(arrays.split_feature)[:n_nodes].astype(np.int32)
        self.threshold_bin = as_np(arrays.threshold_bin)[:n_nodes].astype(np.int32)
        self.threshold = as_np(arrays.threshold)[:n_nodes].astype(np.float64)
        self.default_left = as_np(arrays.default_left)[:n_nodes].astype(bool)
        self.missing_type = as_np(arrays.missing_type)[:n_nodes].astype(np.int32)
        self.left_child = as_np(arrays.left_child)[:n_nodes].astype(np.int32)
        self.right_child = as_np(arrays.right_child)[:n_nodes].astype(np.int32)
        self.split_gain = as_np(arrays.split_gain)[:n_nodes].astype(np.float64)
        self.internal_value = as_np(arrays.internal_value)[:n_nodes].astype(np.float64)
        self.internal_weight = as_np(arrays.internal_weight)[:n_nodes].astype(np.float64)
        self.internal_count = as_np(arrays.internal_count)[:n_nodes].astype(np.int64)
        self.leaf_value = as_np(arrays.leaf_value)[: self.num_leaves].astype(np.float64)
        self.leaf_weight = as_np(arrays.leaf_weight)[: self.num_leaves].astype(np.float64)
        self.leaf_count = as_np(arrays.leaf_count)[: self.num_leaves].astype(np.int64)
        self.leaf_parent = as_np(arrays.leaf_parent)[: self.num_leaves].astype(np.int32)
        self.is_cat = as_np(arrays.is_cat)[:n_nodes].astype(bool)
        self.cat_bitset = as_np(arrays.cat_bitset)[:n_nodes].astype(np.uint32)
        # raw-category sets per node (None for numerical nodes); filled from
        # the bin mappers by GBDT._fill_real_thresholds — the bin->category
        # translation the reference does in Tree::SplitCategorical
        self.cat_sets = [None] * n_nodes
        self.shrinkage = shrinkage

    def cat_bins_of(self, node: int) -> np.ndarray:
        """Bins in node's left set, decoded from the bin-space bitset."""
        words = self.cat_bitset[node]
        bits = np.unpackbits(words.view(np.uint8), bitorder="little")
        return np.flatnonzero(bits)

    def apply_shrinkage(self, rate: float) -> None:
        """reference: Tree::Shrinkage, tree.h:187-196."""
        self.leaf_value *= rate
        self.internal_value *= rate
        self.shrinkage *= rate

    def add_bias(self, val: float) -> None:
        """Fold a constant score into the tree (reference: Tree::AddBias,
        tree.h:198-211 — used to embed the boost-from-average init score into
        the saved model; forces shrinkage to 1)."""
        if val == 0.0:
            return
        self.leaf_value += val
        self.internal_value += val
        self.shrinkage = 1.0

    def set_leaf_values(self, values: np.ndarray) -> None:
        self.leaf_value = np.asarray(values, dtype=np.float64)[: self.num_leaves]

    # -- numpy prediction (exact, host) ------------------------------------
    def _go_left(self, nd: np.ndarray, v: np.ndarray) -> np.ndarray:
        """Vectorized Tree::Decision (reference tree.h:331-339): numerical
        threshold compare or categorical raw-value bitset membership."""
        t = self.threshold[nd]
        dl = self.default_left[nd]
        mt = self.missing_type[nd]
        isnan = np.isnan(v)
        v0 = np.where(isnan, 0.0, v)
        miss = np.where(
            mt == MISSING_NAN, isnan,
            np.where(mt == MISSING_ZERO,
                     isnan | (np.abs(v0) <= K_ZERO_THRESHOLD), False),
        )
        go_left = np.where(miss, dl, v0 <= t)
        cat_rows = self.is_cat[nd]
        if cat_rows.any():
            # reference CategoricalDecision (tree.h:302-320): C truncation
            # cast (static_cast<int>), NOT rounding; negatives and NaN go
            # right (our binning routes both to the other/unseen bin, which
            # is never in the left set)
            vi = np.where(isnan, -1, np.trunc(v0)).astype(np.int64)
            for node in np.unique(nd[cat_rows]):
                m = cat_rows & (nd == node)
                s = self.cat_sets[node]
                if s is None:
                    s = self.cat_bins_of(node)
                go_left[m] = (vi[m] >= 0) & np.isin(vi[m], np.asarray(s))
        return go_left

    def _walk(self, X: np.ndarray):
        """Root-to-leaf walk; returns the leaf index per row."""
        N = X.shape[0]
        leaf = np.zeros(N, dtype=np.int32)
        if self.num_leaves <= 1:
            return leaf
        node = np.zeros(N, dtype=np.int64)
        active = np.ones(N, dtype=bool)
        while active.any():
            nd = node[active]
            f = self.split_feature[nd]
            v = X[active, f].astype(np.float64)
            go_left = self._go_left(nd, v)
            nxt = np.where(go_left, self.left_child[nd], self.right_child[nd])
            node[active] = nxt
            idx = np.flatnonzero(active)
            done = nxt < 0
            leaf[idx[done]] = -nxt[done] - 1
            active[idx[done]] = False
        return leaf

    def predict(self, X: np.ndarray) -> np.ndarray:
        if self.num_leaves < 1:
            return np.zeros(X.shape[0], dtype=np.float64)
        return self.leaf_value[self._walk(X)]

    def predict_leaf_index(self, X: np.ndarray) -> np.ndarray:
        return self._walk(X)

    def to_arrays(self, max_leaves: int) -> TreeArrays:
        L = max_leaves
        L1 = max(L - 1, 1)
        W = self.cat_bitset.shape[1] if self.cat_bitset.ndim == 2 and \
            self.cat_bitset.shape[1] > 0 else 1

        def pad(a, n, dtype, fill=0):
            out = np.full(n, fill, dtype=dtype)
            out[: len(a)] = a
            return jnp.asarray(out)

        bitset = np.zeros((L1, W), np.uint32)
        bitset[: len(self.cat_bitset)] = self.cat_bitset
        return TreeArrays(
            num_leaves=jnp.asarray(self.num_leaves, jnp.int32),
            split_feature=pad(self.split_feature, L1, np.int32),
            threshold_bin=pad(self.threshold_bin, L1, np.int32),
            threshold=pad(self.threshold, L1, np.float32),
            default_left=pad(self.default_left, L1, bool),
            missing_type=pad(self.missing_type, L1, np.int32),
            left_child=pad(self.left_child, L1, np.int32, -1),
            right_child=pad(self.right_child, L1, np.int32, -1),
            split_gain=pad(self.split_gain, L1, np.float32),
            internal_value=pad(self.internal_value, L1, np.float32),
            internal_weight=pad(self.internal_weight, L1, np.float32),
            internal_count=pad(self.internal_count, L1, np.float32),
            leaf_value=pad(self.leaf_value, L, np.float32),
            leaf_weight=pad(self.leaf_weight, L, np.float32),
            leaf_count=pad(self.leaf_count, L, np.float32),
            leaf_parent=pad(self.leaf_parent, L, np.int32, -1),
            is_cat=pad(self.is_cat, L1, bool),
            cat_bitset=jnp.asarray(bitset),
        )
