"""Row-block streaming tree grower — out-of-core training (ROADMAP item 2).

Host-driven replica of the sequential masked leaf-wise grower
(models/grower.py ``make_leafwise_grower(partition=False)`` — the
reference's exact best-first split order) whose O(N) passes are streamed
over row blocks instead of touching a resident (F, N) device matrix:

* per-split **histogram passes** fold each block into a running device
  accumulator (ops/histogram.hist_one_leaf_accum) — scatter-add update
  order makes the streamed fold bit-identical to the resident full-matrix
  pass, so split decisions (and therefore the saved model text) match the
  in-memory trainer BYTE FOR BYTE at fixed block order
  (tests/test_stream_train.py pins this across binary/multiclass/DART);
* per-split **leaf routing** updates each block's host-side leaf-id shard
  with the same ``apply_decision`` ops the resident grower runs;
* blocks stream host→device **double-buffered**: the next block's
  ``device_put`` is issued before the current block's histogram pass is
  consumed (the PR-4 predict-path overlap pattern, applied to training);
* everything leaf-sized (histogram pool, split tables, tree arrays) stays
  on device — tiny, O(L·F·B), row-count-independent.

Peak streaming-owned device bytes are O(block_rows · F) + O(L·F·B) and
are accounted explicitly in a :class:`~lightgbmv1_tpu.data.DeviceLedger`
(asserted by the memory-guard test and the BENCH ``stream_ok`` field).

Scope: the streaming schedule is the sequential best-first order (the
parity configuration — ``tree_growth=leafwise_masked`` /
``leafwise_wave_size=1``); forced splits, CEGB and EFB bundles are
resident-trainer-only and are rejected loudly at construction
(models/gbdt_stream.py).  4-bit packed caches (block-cache v3
``bin_layout=packed4``, ISSUE 18) stream their PACKED shards: the H2D
transfer moves ``(ceil(F/2), rows)`` bytes and each per-block jit
unpacks nibbles on device first (``unpack4bit`` — exact, so the fold
stays bit-identical to the unpacked stream at fixed block order).
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..io.binning import MISSING_NAN, MISSING_ZERO
from ..ops.hist_pallas import unpack4bit
from ..ops.histogram import hist_one_leaf_accum, sums_accum
from ..ops.split import (NO_CONSTRAINT, FeatureMeta, SplitParams,
                         find_best_split, leaf_output, smooth_output)
from .grower import _node_feature_mask, allowed_features_for
from .tree import TreeArrays, empty_tree


class StreamState(NamedTuple):
    """Leaf-sized grower state (the GrowerState of models/grower.py minus
    every O(N) member — those live host-side in block shards)."""

    hist_pool: jax.Array      # (L, F, B, 3) or (1, 1, 1, 3) pool-free
    leaf_sums: jax.Array      # (L, 3)
    leaf_depth: jax.Array     # (L,)
    best_gain: jax.Array      # (L,)
    best_feat: jax.Array
    best_bin: jax.Array
    best_dl: jax.Array
    best_left: jax.Array      # (L, 3)
    best_right: jax.Array
    best_iscat: jax.Array
    best_bitset: jax.Array    # (L, W)
    leaf_constr: jax.Array    # (L, 2)
    leaf_out: jax.Array       # (L,)
    leaf_used: jax.Array      # (L, F)
    tree: TreeArrays
    leaf_is_left: jax.Array
    num_leaves: jax.Array


class StreamGrower:
    """grow(g3_host, base_mask, key) over a block source.

    ``source``: data/streaming block source (disk cache or in-memory
    wrap).  ``ledger``: DeviceLedger recording every device buffer this
    grower creates.  The numeric contract: identical ops, in identical
    order, to the resident masked grower — every formula below mirrors
    models/grower.py's ``make_leafwise_grower`` body (which stays the
    source of truth; the parity tests fail if they drift apart)."""

    def __init__(
        self,
        *,
        source,
        ledger,
        num_leaves: int,
        num_bins: int,
        meta: FeatureMeta,
        params: SplitParams,
        max_depth: int = -1,
        feature_fraction_bynode: float = 1.0,
        monotone_penalty: float = 0.0,
        interaction_groups=None,
        hist_method: str = "scatter",
        hist_precision: str = "bf16x2",
        hist_pool_mb: float = -1.0,
        prefetch: bool = True,
    ):
        self.source = source
        self.ledger = ledger
        self.L = num_leaves
        self.B = num_bins
        self.meta = meta
        self.params = params
        self.max_depth = max_depth
        self.ffbn = feature_fraction_bynode
        self.mono_penalty = monotone_penalty
        self.method = hist_method
        self.precision = hist_precision
        self.prefetch = prefetch
        self.F = int(np.asarray(meta.num_bins).shape[0])
        # packed cache shards: H2D moves the packed bytes; each per-block
        # jit decodes nibbles on device first (_unpack below)
        self.packed_src = (getattr(source, "bin_layout", "u8")
                           == "packed4")
        self.use_mc = bool(np.asarray(meta.monotone_type).any())
        self.groups = (jnp.asarray(interaction_groups)
                       if interaction_groups is not None else None)
        # pool sizing: the same 512 MB auto bound as the resident grower —
        # the pool/pool-free decision changes the subtraction arithmetic,
        # so parity requires the SAME decision on both sides
        pool_bytes = float(self.L) * self.F * self.B * 3 * 4
        cap_bytes = (hist_pool_mb * (1 << 20) if hist_pool_mb > 0
                     else 512.0 * (1 << 20))
        self.use_pool = pool_bytes <= cap_bytes
        self._decide_jit = jax.jit(self._decide)
        self._root_jit = jax.jit(self._root_init)
        self._read_jit = jax.jit(self._read_split)
        self._apply_jit = jax.jit(self._apply_block)
        # one dispatch per block per pass: partition + histogram fold(s)
        # fused (every op inside is exact — 0/1-mask multiplies, integer
        # compares, ordered scatter adds — so fusion cannot move a bit)
        self._root_block_jit = jax.jit(self._root_block)
        self._split_block_jit = jax.jit(self._split_block)

    # -- jitted pieces (each mirrors a slice of grower.py's body) -------
    def _split_fn(self, hist, parent, mask, key, uid, constraint, depth,
                  parent_output):
        rk = jax.random.fold_in(key, uid + 1_000_003 + self.params.extra_seed) \
            if self.params.extra_trees else None
        return find_best_split(hist, parent, self.meta, mask, self.params,
                               constraint, depth, self.mono_penalty,
                               parent_output, rk, None)

    def _clamp_out(self, sums, constr, parent_out=0.0):
        out = leaf_output(sums[0], sums[1], self.params)
        if self.params.path_smooth > 0:
            out = smooth_output(out, sums[2], parent_out, self.params)
        if not self.use_mc:
            return out
        return jnp.clip(out, constr[0], constr[1])

    def _allowed(self, used):
        return allowed_features_for(self.groups, used)

    def _apply_block(self, bins_blk, lid_blk, leaf, nl, feat, thr, dl,
                     iscat, bitset):
        """The masked grower's apply_decision, on one block's rows."""
        meta = self.meta
        with jax.named_scope("lgbm.partition"):
            bins_f = bins_blk[feat]
            is_na = ((meta.missing_type[feat] == MISSING_NAN)
                     & (bins_f == meta.nan_bin[feat])) | (
                (meta.missing_type[feat] == MISSING_ZERO)
                & (bins_f == meta.zero_bin[feat]))
            go_left = jnp.where(is_na, dl, bins_f <= thr)
            bi = bins_f.astype(jnp.int32)
            word = bitset[bi >> 5]
            in_set = ((word >> (bi.astype(jnp.uint32) & 31)) & 1) == 1
            go_left = jnp.where(iscat, in_set, go_left)
            return jnp.where((lid_blk == leaf) & (~go_left), nl, lid_blk)

    def _unpack(self, bins_blk):
        """Device-side nibble decode of a packed block — exact, so every
        downstream fold sees the same uint8 bins as an unpacked stream."""
        return (unpack4bit(bins_blk, self.F) if self.packed_src
                else bins_blk)

    def _root_block(self, acc, rs, bins_blk, g3_blk):
        """Root pass, one block, one dispatch: histogram fold + ordered
        root-sum fold."""
        bins_blk = self._unpack(bins_blk)
        acc = hist_one_leaf_accum(
            acc, bins_blk, g3_blk, jnp.zeros(g3_blk.shape[0], jnp.int32),
            jnp.asarray(0, jnp.int32), self.B, method=self.method,
            precision=self.precision)
        return acc, sums_accum(rs, g3_blk)

    def _split_block(self, acc_s, acc_l, bins_blk, g3_blk, lid_blk, leaf,
                     nl, feat, thr, dl, iscat, bitset, smaller, larger):
        """Split pass, one block, one dispatch: route the block's rows
        through the split, then fold the smaller (and, pool-free, the
        larger) child's histogram."""
        bins_blk = self._unpack(bins_blk)
        lid2 = self._apply_block(bins_blk, lid_blk, leaf, nl, feat, thr,
                                 dl, iscat, bitset)
        acc_s = hist_one_leaf_accum(acc_s, bins_blk, g3_blk, lid2,
                                    smaller, self.B, method=self.method,
                                    precision=self.precision)
        if not self.use_pool:
            acc_l = hist_one_leaf_accum(acc_l, bins_blk, g3_blk, lid2,
                                        larger, self.B,
                                        method=self.method,
                                        precision=self.precision)
        return lid2, acc_s, acc_l

    def _root_init(self, hist0, root_sum, base_mask, key):
        L, F = self.L, self.F
        mask0 = _node_feature_mask(key, 0, base_mask, self.ffbn)
        used0 = jnp.zeros(F, bool)
        mask0 = mask0 & self._allowed(used0)
        no_constr = jnp.asarray(NO_CONSTRAINT, jnp.float32)
        out0 = leaf_output(root_sum[0], root_sum[1], self.params)
        if self.params.path_smooth > 0:
            out0 = smooth_output(out0, root_sum[2], 0.0, self.params)
        res0 = self._split_fn(hist0, root_sum, mask0, key, 0, no_constr, 0,
                              out0)
        W = res0.cat_bitset.shape[0]
        return StreamState(
            hist_pool=(jnp.zeros((L,) + hist0.shape,
                                 jnp.float32).at[0].set(hist0)
                       if self.use_pool
                       else jnp.zeros((1, 1, 1, 3), jnp.float32)),
            leaf_sums=jnp.zeros((L, 3), jnp.float32).at[0].set(root_sum),
            leaf_depth=jnp.zeros(L, jnp.int32),
            best_gain=jnp.full(L, -jnp.inf,
                               jnp.float32).at[0].set(res0.gain),
            best_feat=jnp.zeros(L, jnp.int32).at[0].set(res0.feature),
            best_bin=jnp.zeros(L, jnp.int32).at[0].set(res0.threshold_bin),
            best_dl=jnp.zeros(L, bool).at[0].set(res0.default_left),
            best_left=jnp.zeros((L, 3), jnp.float32).at[0].set(res0.left_sum),
            best_right=jnp.zeros((L, 3),
                                 jnp.float32).at[0].set(res0.right_sum),
            best_iscat=jnp.zeros(L, bool).at[0].set(res0.is_cat),
            best_bitset=jnp.zeros((L, W),
                                  jnp.uint32).at[0].set(res0.cat_bitset),
            leaf_constr=jnp.tile(jnp.asarray(NO_CONSTRAINT, jnp.float32),
                                 (L, 1)),
            leaf_out=jnp.zeros(L, jnp.float32).at[0].set(out0),
            leaf_used=jnp.zeros((L, F), bool),
            tree=empty_tree(L, W),
            leaf_is_left=jnp.zeros(L, bool),
            num_leaves=jnp.asarray(1, jnp.int32),
        )

    def _read_split(self, st: StreamState, leaf):
        """Everything the host block pass needs about the chosen split."""
        return (st.best_feat[leaf], st.best_bin[leaf], st.best_dl[leaf],
                st.best_iscat[leaf], st.best_bitset[leaf],
                st.best_left[leaf], st.best_right[leaf], st.num_leaves)

    def _decide(self, st: StreamState, leaf, s, h_small, h_large,
                base_mask, key):
        """do_split minus the O(N) partition/histogram passes (already
        streamed by the caller); line-for-line with grower.py."""
        meta, params = self.meta, self.params
        nl = st.num_leaves
        node = nl - 1
        feat = st.best_feat[leaf]
        thr = st.best_bin[leaf]
        dl = st.best_dl[leaf]
        lsum = st.best_left[leaf]
        rsum = st.best_right[leaf]
        iscat = st.best_iscat[leaf]
        bitset = st.best_bitset[leaf]
        gain = st.best_gain[leaf]
        parent_sum = st.leaf_sums[leaf]

        pconstr = st.leaf_constr[leaf]
        pout = st.leaf_out[leaf]
        out_l = self._clamp_out(lsum, pconstr, pout)
        out_r = self._clamp_out(rsum, pconstr, pout)
        if self.use_mc:
            mono = meta.monotone_type[feat]
            mid = 0.5 * (out_l + out_r)
            upd = (~iscat) & (mono != 0)
            new_max_l = jnp.where(upd & (mono > 0),
                                  jnp.minimum(pconstr[1], mid), pconstr[1])
            new_min_l = jnp.where(upd & (mono < 0),
                                  jnp.maximum(pconstr[0], mid), pconstr[0])
            new_max_r = jnp.where(upd & (mono < 0),
                                  jnp.minimum(pconstr[1], mid), pconstr[1])
            new_min_r = jnp.where(upd & (mono > 0),
                                  jnp.maximum(pconstr[0], mid), pconstr[0])
            constr_l = jnp.stack([new_min_l, new_max_l])
            constr_r = jnp.stack([new_min_r, new_max_r])
        else:
            constr_l = constr_r = pconstr

        smaller_is_left = lsum[2] <= rsum[2]
        if self.use_pool:
            h_parent = st.hist_pool[leaf]
            h_left = jnp.where(smaller_is_left, h_small,
                               h_parent - h_small)
            h_right = h_parent - h_left
            pool = st.hist_pool.at[leaf].set(h_left).at[nl].set(h_right)
        else:
            h_left = jnp.where(smaller_is_left, h_small, h_large)
            h_right = jnp.where(smaller_is_left, h_large, h_small)
            pool = st.hist_pool

        d = st.leaf_depth[leaf] + 1
        depth_ok = (self.max_depth <= 0) | (d < self.max_depth)

        used_child = st.leaf_used[leaf].at[feat].set(True)
        allow_child = self._allowed(used_child)
        mask_l = _node_feature_mask(key, 2 * s + 1, base_mask,
                                    self.ffbn) & allow_child
        mask_r = _node_feature_mask(key, 2 * s + 2, base_mask,
                                    self.ffbn) & allow_child
        res_l = self._split_fn(h_left, lsum, mask_l, key, 2 * s + 1,
                               constr_l, d, out_l)
        res_r = self._split_fn(h_right, rsum, mask_r, key, 2 * s + 2,
                               constr_r, d, out_r)
        gain_l = jnp.where(depth_ok, res_l.gain, -jnp.inf)
        gain_r = jnp.where(depth_ok, res_r.gain, -jnp.inf)

        t = st.tree
        p = t.leaf_parent[leaf]
        p_safe = jnp.maximum(p, 0)
        was_left = st.leaf_is_left[leaf]
        lc = t.left_child.at[p_safe].set(
            jnp.where((p >= 0) & was_left, node, t.left_child[p_safe]))
        rc = t.right_child.at[p_safe].set(
            jnp.where((p >= 0) & (~was_left), node, t.right_child[p_safe]))
        lc = lc.at[node].set(-(leaf + 1))
        rc = rc.at[node].set(-(nl + 1))
        tree = t._replace(
            num_leaves=nl + 1,
            split_feature=t.split_feature.at[node].set(feat),
            threshold_bin=t.threshold_bin.at[node].set(thr),
            default_left=t.default_left.at[node].set(dl),
            is_cat=t.is_cat.at[node].set(iscat),
            cat_bitset=t.cat_bitset.at[node].set(bitset),
            missing_type=t.missing_type.at[node].set(
                meta.missing_type[feat]),
            left_child=lc,
            right_child=rc,
            split_gain=t.split_gain.at[node].set(gain),
            internal_value=t.internal_value.at[node].set(pout),
            internal_weight=t.internal_weight.at[node].set(parent_sum[1]),
            internal_count=t.internal_count.at[node].set(parent_sum[2]),
            leaf_value=t.leaf_value.at[leaf].set(out_l).at[nl].set(out_r),
            leaf_weight=t.leaf_weight.at[leaf].set(lsum[1])
            .at[nl].set(rsum[1]),
            leaf_count=t.leaf_count.at[leaf].set(lsum[2])
            .at[nl].set(rsum[2]),
            leaf_parent=t.leaf_parent.at[leaf].set(node).at[nl].set(node),
        )

        return StreamState(
            hist_pool=pool,
            leaf_sums=st.leaf_sums.at[leaf].set(lsum).at[nl].set(rsum),
            leaf_depth=st.leaf_depth.at[leaf].set(d).at[nl].set(d),
            best_gain=st.best_gain.at[leaf].set(gain_l).at[nl].set(gain_r),
            best_feat=st.best_feat.at[leaf].set(res_l.feature)
            .at[nl].set(res_r.feature),
            best_bin=st.best_bin.at[leaf].set(res_l.threshold_bin)
            .at[nl].set(res_r.threshold_bin),
            best_dl=st.best_dl.at[leaf].set(res_l.default_left)
            .at[nl].set(res_r.default_left),
            best_left=st.best_left.at[leaf].set(res_l.left_sum)
            .at[nl].set(res_r.left_sum),
            best_right=st.best_right.at[leaf].set(res_l.right_sum)
            .at[nl].set(res_r.right_sum),
            best_iscat=st.best_iscat.at[leaf].set(res_l.is_cat)
            .at[nl].set(res_r.is_cat),
            best_bitset=st.best_bitset.at[leaf].set(res_l.cat_bitset)
            .at[nl].set(res_r.cat_bitset),
            leaf_constr=st.leaf_constr.at[leaf].set(constr_l)
            .at[nl].set(constr_r),
            leaf_out=st.leaf_out.at[leaf].set(out_l).at[nl].set(out_r),
            leaf_used=st.leaf_used.at[leaf].set(used_child)
            .at[nl].set(used_child),
            tree=tree,
            leaf_is_left=st.leaf_is_left.at[leaf].set(True)
            .at[nl].set(False),
            num_leaves=nl + 1,
        )

    # -- host-side block streaming --------------------------------------
    def _upload(self, i: int, g3_host, lid_host=None):
        """device_put one block's shards (async — the double-buffer leg);
        returns (bins, g3, lid, handles)."""
        from ..obs import trace

        a, b = self.source.ranges[i]
        with trace.span("stream.fetch_block", cat="stream",
                        args={"block": i} if trace.enabled() else None):
            blk = self.source.load_block(i)
        with trace.span("stream.h2d_block", cat="stream",
                        args={"block": i} if trace.enabled() else None):
            bins = jax.device_put(blk)
            g3 = jax.device_put(np.ascontiguousarray(g3_host[a:b]))
            handles = [self.ledger.hold_array("block_bins", bins),
                       self.ledger.hold_array("block_g3", g3)]
            lid = None
            if lid_host is not None:
                lid = jax.device_put(np.ascontiguousarray(lid_host[a:b]))
                handles.append(self.ledger.hold_array("block_lid", lid))
        return bins, g3, lid, handles

    def _release(self, handles):
        if handles is None:
            return
        if isinstance(handles, int):
            self.ledger.release(handles)
            return
        for h in handles:
            self.ledger.release(h)

    def _stream_blocks(self, g3_host, lid_host, fn):
        """Run ``fn(i, a, b, bins, g3, lid)`` per block with the next
        block's H2D transfer in flight behind the current block's compute
        (the PR-4 chunked double-buffer pattern)."""
        from ..obs import trace

        nb = self.source.num_blocks
        nxt = None
        for i in range(nb):
            cur = nxt if nxt is not None else self._upload(i, g3_host,
                                                           lid_host)
            nxt = (self._upload(i + 1, g3_host, lid_host)
                   if (self.prefetch and i + 1 < nb) else None)
            bins, g3, lid, handles = cur
            a, b = self.source.ranges[i]
            with trace.span("stream.accumulate", cat="stream",
                            args=({"block": i, "rows": b - a}
                                  if trace.enabled() else None)):
                fn(i, a, b, bins, g3, lid)
            self._release(handles)

    def _zero_hist(self, tag):
        acc = jnp.zeros((self.F, self.B, 3), jnp.float32)
        return acc, self.ledger.hold_array(tag, acc)

    def grow(self, g3_host: np.ndarray, base_mask, key):
        """-> (TreeArrays, leaf_id_host (N,) int32, root_sum).  Same split
        sequence and f32 values as the resident masked grower given the
        same g3."""
        L = self.L
        N = self.source.num_rows
        lid_host = np.zeros(N, np.int32)
        base_mask = jnp.asarray(base_mask)

        # root pass: full-matrix histogram + root-sum fold over blocks
        acc, h_acc = self._zero_hist("hist_acc")
        rs = jnp.zeros((1, 3), jnp.float32)

        def root_fn(i, a, b, bins, g3, lid):
            nonlocal acc, rs
            acc, rs = self._root_block_jit(acc, rs, bins, g3)

        self._stream_blocks(g3_host, None, root_fn)
        root_sum = rs[0]
        st = self._root_jit(acc, root_sum, base_mask, key)
        self._release(h_acc)
        pool_h = (self.ledger.hold_array("hist_pool", st.hist_pool)
                  if self.use_pool else None)

        if L > 1:
            for s in range(L - 1):
                best_gain = np.asarray(jax.device_get(st.best_gain))
                leaf = int(np.argmax(best_gain))
                if not (best_gain[leaf] > 0):
                    break   # the resident grower's done latch
                (feat, thr, dl, iscat, bitset, lsum, rsum,
                 nl) = jax.device_get(self._read_jit(st, leaf))
                nl = int(nl)
                smaller = leaf if float(lsum[2]) <= float(rsum[2]) else nl
                larger = nl if smaller == leaf else leaf

                acc_s, h_s = self._zero_hist("hist_acc")
                h_l = None
                if self.use_pool:
                    acc_l = jnp.zeros((1, 1, 3), jnp.float32)  # unused leg
                else:
                    acc_l, h_l = self._zero_hist("hist_acc")
                feat_d = jnp.asarray(int(feat), jnp.int32)
                thr_d = jnp.asarray(int(thr), jnp.int32)
                dl_d = jnp.asarray(bool(dl))
                iscat_d = jnp.asarray(bool(iscat))
                bitset_d = jnp.asarray(bitset)
                leaf_d = jnp.asarray(leaf, jnp.int32)
                nl_d = jnp.asarray(nl, jnp.int32)
                sm_d = jnp.asarray(smaller, jnp.int32)
                lg_d = jnp.asarray(larger, jnp.int32)

                def split_fn(i, a, b, bins, g3, lid):
                    nonlocal acc_s, acc_l
                    lid2, acc_s, acc_l = self._split_block_jit(
                        acc_s, acc_l, bins, g3, lid, leaf_d, nl_d, feat_d,
                        thr_d, dl_d, iscat_d, bitset_d, sm_d, lg_d)
                    lid_host[a:b] = np.asarray(jax.device_get(lid2))

                self._stream_blocks(g3_host, lid_host, split_fn)
                h_large = (acc_l if not self.use_pool
                           else jnp.zeros_like(acc_s))
                st = self._decide_jit(st, leaf_d, jnp.asarray(s, jnp.int32),
                                      acc_s, h_large, base_mask, key)
                self._release(h_s)
                self._release(h_l)
        self._release(pool_h)
        return st.tree, lid_host, root_sum
