"""Feature binning (host-side preprocessing).

TPU-native re-design of the reference BinMapper (reference:
``src/io/bin.cpp`` — ``BinMapper::FindBin`` bin.cpp:325, ``GreedyFindBin``
bin.cpp:78, ``FindBinWithZeroAsOneBin`` bin.cpp:256, ``ValueToBin``
include/LightGBM/bin.h:457-495).

Differences from the reference, by design (SURVEY.md §7 "Hard parts"):

* **Full bins, no most-frequent-bin elision.**  The reference reserves bin 0
  for the most frequent bin per feature group and recovers it later via
  ``FixHistogram`` (dataset.cpp:1410).  On TPU the histogram for every bin is
  free (dense MXU matmul), so we store every bin explicitly and never need
  FixHistogram.  This also removes the per-group ``bin_offsets`` bookkeeping.
* **Exclusive feature bundling (EFB) lives one layer up.**  The binned
  layout is a dense ``(num_features, num_data)`` integer matrix; when EFB is
  enabled (``enable_bundle``), ``io/bundle.py`` merges mutually-exclusive
  sparse features into shared columns of that matrix AFTER binning
  (reference: dataset.cpp:97-235), so this module stays bundling-agnostic.

Semantics preserved: greedy equal-count bin boundary search on a sample,
zero-straddling bins, missing handling (None/Zero/NaN with a trailing NaN
bin), categorical binning by descending frequency, trivial-feature detection.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

# reference: include/LightGBM/bin.h kZeroThreshold
K_ZERO_THRESHOLD = 1e-35
# missing types (reference: enum MissingType, bin.h)
MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2

BIN_NUMERICAL = 0
BIN_CATEGORICAL = 1


def _need_filter(cnt_in_bin: np.ndarray, total_cnt: int, filter_cnt: int,
                 bin_type: int) -> bool:
    """feature_pre_filter test (behavioral port of NeedFilter, reference
    src/io/bin.cpp:54-76): True when NO split point of this feature can put
    >= filter_cnt samples on both sides — such a feature can never satisfy
    min_data_in_leaf and is marked trivial up front."""
    cnt = np.asarray(cnt_in_bin, dtype=np.int64)
    if len(cnt) < 2:
        return True
    if bin_type == BIN_NUMERICAL:
        left = np.cumsum(cnt[:-1])
        return not bool(np.any((left >= filter_cnt)
                               & (total_cnt - left >= filter_cnt)))
    # categorical: the reference only filters 2-bin features (one-vs-rest
    # splits on >2 bins are not prefix sums, bin.cpp:63-73)
    if len(cnt) > 2:
        return False
    left = cnt[:-1]
    return not bool(np.any((left >= filter_cnt)
                           & (total_cnt - left >= filter_cnt)))


def _upper_bound_1ulp(a: float) -> float:
    """Common::GetDoubleUpperBound (reference utils/common.h:931)."""
    return float(np.nextafter(a, np.inf))


def _eq_ordered(a: float, b: float) -> bool:
    """Common::CheckDoubleEqualOrdered for sorted a <= b
    (reference utils/common.h:926): b within one ulp above a."""
    return b <= np.nextafter(a, np.inf)


def _greedy_find_bin(
    distinct_values: np.ndarray,
    counts: np.ndarray,
    max_bin: int,
    total_cnt: int,
    min_data_in_bin: int,
) -> List[float]:
    """Greedy equal-count boundary search — exact behavioral port of
    GreedyFindBin (reference src/io/bin.cpp:78-156), including the
    adaptive mean-bin-size recomputation, the big-count-value lookahead,
    and the one-ulp boundary dedupe, so bin boundaries agree with the
    reference bit-for-bit on the same sample."""
    bounds: List[float] = []
    nd = len(distinct_values)
    if nd == 0:
        return [math.inf]
    if nd <= max_bin:
        cur = 0
        for i in range(nd - 1):
            cur += int(counts[i])
            if cur >= min_data_in_bin:
                val = _upper_bound_1ulp(
                    (distinct_values[i] + distinct_values[i + 1]) / 2.0)
                if not bounds or not _eq_ordered(bounds[-1], val):
                    bounds.append(val)
                    cur = 0
        bounds.append(math.inf)
        return bounds

    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, int(total_cnt) // min_data_in_bin))
    mean_bin_size = total_cnt / max_bin

    rest_bin_cnt = max_bin
    rest_sample_cnt = int(total_cnt)
    is_big = np.asarray(counts, np.int64) >= mean_bin_size
    rest_bin_cnt -= int(is_big.sum())
    rest_sample_cnt -= int(counts[is_big].sum())

    def _mean(cnt, bins):
        if bins != 0:
            return cnt / bins
        return math.inf if cnt > 0 else math.nan

    mean_bin_size = _mean(rest_sample_cnt, rest_bin_cnt)
    upper = [math.inf] * max_bin
    lower = [math.inf] * max_bin
    bin_cnt = 0
    lower[0] = float(distinct_values[0])
    cur = 0
    for i in range(nd - 1):
        if not is_big[i]:
            rest_sample_cnt -= int(counts[i])
        cur += int(counts[i])
        if (is_big[i] or cur >= mean_bin_size
                or (is_big[i + 1] and cur >= max(1.0, mean_bin_size * 0.5))):
            upper[bin_cnt] = float(distinct_values[i])
            bin_cnt += 1
            lower[bin_cnt] = float(distinct_values[i + 1])
            if bin_cnt >= max_bin - 1:
                break
            cur = 0
            if not is_big[i]:
                rest_bin_cnt -= 1
                mean_bin_size = _mean(rest_sample_cnt, rest_bin_cnt)
    bin_cnt += 1
    for i in range(bin_cnt - 1):
        val = _upper_bound_1ulp((upper[i] + lower[i + 1]) / 2.0)
        if not bounds or not _eq_ordered(bounds[-1], val):
            bounds.append(val)
    bounds.append(math.inf)
    return bounds


def _find_bin_with_zero_as_one_bin(
    distinct_values: np.ndarray,
    counts: np.ndarray,
    max_bin: int,
    total_sample_cnt: int,
    min_data_in_bin: int,
) -> List[float]:
    """Ensure one bin straddles zero — exact behavioral port of
    FindBinWithZeroAsOneBin (reference src/io/bin.cpp:256-312): the
    negative range gets a count-proportional share of ``max_bin - 1`` bins
    (denominator excludes the zero count), the zero bin closes at
    ``kZeroThreshold``, and the positive range takes the remainder."""
    dv = np.asarray(distinct_values, np.float64)
    left_cnt_data = int(counts[dv <= -K_ZERO_THRESHOLD].sum())
    right_cnt_data = int(counts[dv > K_ZERO_THRESHOLD].sum())
    cnt_zero = int(total_sample_cnt) - left_cnt_data - right_cnt_data

    left_cnt = int(np.argmax(dv > -K_ZERO_THRESHOLD)) \
        if bool((dv > -K_ZERO_THRESHOLD).any()) else len(dv)

    bounds: List[float] = []
    if left_cnt > 0 and max_bin > 1:
        denom = total_sample_cnt - cnt_zero
        left_max_bin = int(left_cnt_data / max(denom, 1) * (max_bin - 1))
        left_max_bin = max(1, left_max_bin)
        bounds = _greedy_find_bin(dv[:left_cnt], counts[:left_cnt],
                                  left_max_bin, left_cnt_data,
                                  min_data_in_bin)
        if bounds:
            bounds[-1] = -K_ZERO_THRESHOLD

    right_pos = np.nonzero(dv[left_cnt:] > K_ZERO_THRESHOLD)[0]
    right_start = left_cnt + int(right_pos[0]) if len(right_pos) else -1

    right_max_bin = max_bin - 1 - len(bounds)
    # when positives exist but right_max_bin == 0 (tiny max_bin with data on
    # both sides of zero), the reference ALSO falls into the inf-only branch
    # (bin.cpp:302-309 appends infinity, not kZeroThreshold) — keep parity
    if right_start >= 0 and right_max_bin > 0:
        rb = _greedy_find_bin(dv[right_start:], counts[right_start:],
                              right_max_bin, right_cnt_data, min_data_in_bin)
        bounds.append(K_ZERO_THRESHOLD)
        bounds.extend(rb)
    else:
        bounds.append(math.inf)
    return bounds


def _distinct_with_zero(values_sorted: np.ndarray, zero_cnt: int):
    """Distinct values + counts from a SORTED non-NaN sample — behavioral
    port of the reference's construction (src/io/bin.cpp:352-390):
    neighbouring values within one ulp merge (keeping the larger value),
    and the implicit-zero count is spliced in where zero sorts (front /
    between the sign change / back)."""
    n = len(values_sorted)
    if n == 0:
        return np.array([0.0]), np.array([zero_cnt], np.int64)
    v = values_sorted
    # group boundaries: value i starts a new group when NOT within one ulp
    # of value i-1 (CheckDoubleEqualOrdered on consecutive sample values)
    new_grp = np.empty(n, bool)
    new_grp[0] = True
    new_grp[1:] = v[1:] > np.nextafter(v[:-1], np.inf)
    gid = np.cumsum(new_grp) - 1
    counts = np.bincount(gid).astype(np.int64)
    ends = np.cumsum(counts) - 1
    distinct = v[ends]                 # reference keeps the LARGE value
    starts = ends - counts + 1

    out_v: List[float] = []
    out_c: List[int] = []
    if v[0] > 0.0 and zero_cnt > 0:
        out_v.append(0.0)
        out_c.append(zero_cnt)
    for g in range(len(distinct)):
        if g > 0 and v[starts[g] - 1] < 0.0 and v[starts[g]] > 0.0:
            # sign change between consecutive sample values: splice zero
            # (reference pushes it with zero_cnt even when that is 0)
            out_v.append(0.0)
            out_c.append(zero_cnt)
        out_v.append(float(distinct[g]))
        out_c.append(int(counts[g]))
    if v[-1] < 0.0 and zero_cnt > 0:
        out_v.append(0.0)
        out_c.append(zero_cnt)
    return np.asarray(out_v, np.float64), np.asarray(out_c, np.int64)


def _find_bin_with_predefined(
    distinct_values: np.ndarray,
    counts: np.ndarray,
    max_bin: int,
    total_sample_cnt: int,
    min_data_in_bin: int,
    forced_upper_bounds: Sequence[float],
) -> List[float]:
    """Bin boundaries honoring user-forced upper bounds (behavioral port of
    FindBinWithPredefinedBin, reference src/io/bin.cpp:157-255): seed the
    boundary list with the zero-straddle bounds plus the forced bounds, then
    subdivide each seeded range greedily with a bin budget proportional to
    its sample count."""
    bounds: List[float] = []
    # negative / zero / positive partition (reference :163-195)
    left_cnt = int(np.searchsorted(distinct_values, -K_ZERO_THRESHOLD,
                                   side="right"))
    has_left = left_cnt > 0
    right_start = int(np.searchsorted(distinct_values, K_ZERO_THRESHOLD,
                                      side="right"))
    has_right = right_start < len(distinct_values)
    if max_bin == 2:
        bounds.append(K_ZERO_THRESHOLD if left_cnt == 0 else -K_ZERO_THRESHOLD)
    elif max_bin >= 3:
        if has_left:
            bounds.append(-K_ZERO_THRESHOLD)
        if has_right:
            bounds.append(K_ZERO_THRESHOLD)
    bounds.append(math.inf)

    # insert forced bounds (nonzero only — zero bounds already seeded)
    max_to_insert = max_bin - len(bounds)
    inserted = 0
    for b in forced_upper_bounds:
        if inserted >= max_to_insert:
            break
        if abs(b) > K_ZERO_THRESHOLD:
            bounds.append(float(b))
            inserted += 1
    bounds.sort()

    # subdivide each seeded range with a count-proportional budget
    free_bins = max_bin - len(bounds)
    to_add: List[float] = []
    value_ind = 0
    for i, ub in enumerate(bounds):
        bin_start = value_ind
        cnt_in_bin = 0
        while (value_ind < len(distinct_values)
               and distinct_values[value_ind] < ub):
            cnt_in_bin += int(counts[value_ind])
            value_ind += 1
        remaining = max_bin - len(bounds) - len(to_add)
        # std::lround = half away from zero (Python round() would banker-round)
        num_sub = int(math.floor(
            cnt_in_bin * free_bins / max(total_sample_cnt, 1) + 0.5))
        num_sub = min(num_sub, remaining) + 1
        if i == len(bounds) - 1:
            num_sub = remaining + 1
        if num_sub > 1 and value_ind > bin_start:
            sub = _greedy_find_bin(
                distinct_values[bin_start:value_ind],
                counts[bin_start:value_ind],
                num_sub, cnt_in_bin, min_data_in_bin)
            to_add.extend(sub[:-1])          # last bound is +inf
    bounds.extend(to_add)
    return sorted(set(bounds))


def get_forced_bins(path: str, num_total_features: int,
                    categorical_features=None) -> List[List[float]]:
    """forcedbins_filename JSON -> per-feature forced upper bounds
    (behavioral port of DatasetLoader::GetForcedBins,
    reference src/io/dataset_loader.cpp:1200-1235; format:
    ``[{"feature": i, "bin_upper_bound": [..]}, ...]``)."""
    import json

    from ..utils.log import log_warning

    forced: List[List[float]] = [[] for _ in range(num_total_features)]
    if not path:
        return forced
    categorical = set(categorical_features or [])
    from ..utils.fileio import open_file

    try:
        with open_file(path) as fh:
            spec = json.load(fh)
    except OSError:
        log_warning(f"Could not open {path}. Will ignore.")
        return forced
    except json.JSONDecodeError as e:
        from ..utils.log import log_fatal
        log_fatal(f"Forced bins file {path} is not valid JSON: {e}")
    for entry in spec:
        f = int(entry["feature"])
        if f >= num_total_features or f < 0:
            # reference: CHECK_LT(forced_bins_arr[i]["feature"].int_value(),
            # num_total_features) aborts (dataset_loader.cpp:1217)
            from ..utils.log import log_fatal
            log_fatal(f"Forced bins feature index {f} is out of range "
                      f"(num features = {num_total_features})")
        if f in categorical:
            log_warning(f"Feature {f} is categorical. Will ignore forced "
                        "bins for this feature.")
            continue
        forced[f] = [float(b) for b in entry["bin_upper_bound"]]
    # remove consecutive duplicates (reference std::unique)
    for f in range(num_total_features):
        out: List[float] = []
        for b in forced[f]:
            if not out or b != out[-1]:
                out.append(b)
        forced[f] = out
    return forced


@dataclass
class BinMapper:
    """Maps raw feature values to small integer bins (one per feature)."""

    bin_upper_bound: np.ndarray = field(default_factory=lambda: np.array([np.inf]))
    num_bin: int = 1
    missing_type: int = MISSING_NONE
    bin_type: int = BIN_NUMERICAL
    is_trivial: bool = True
    sparse_rate: float = 0.0
    min_value: float = 0.0
    max_value: float = 0.0
    # categorical
    categorical_2_bin: Dict[int, int] = field(default_factory=dict)
    bin_2_categorical: List[int] = field(default_factory=list)

    # ------------------------------------------------------------------
    @property
    def nan_bin(self) -> int:
        """Bin index holding NaN values; -1 if none."""
        if self.bin_type == BIN_CATEGORICAL:
            return self.num_bin - 1  # the "other/unseen" bin also takes NaN
        return self.num_bin - 1 if self.missing_type == MISSING_NAN else -1

    @property
    def zero_bin(self) -> int:
        if self.bin_type == BIN_CATEGORICAL:
            return int(self.categorical_2_bin.get(0, self.num_bin - 1))
        return int(np.searchsorted(self.bin_upper_bound, 0.0, side="left"))

    @property
    def default_bin(self) -> int:
        """Bin that missing values fall into during training."""
        if self.missing_type == MISSING_NAN:
            return self.nan_bin
        return self.zero_bin

    # ------------------------------------------------------------------
    @classmethod
    def find_bin(
        cls,
        sample_values: np.ndarray,
        total_sample_cnt: int,
        max_bin: int,
        min_data_in_bin: int = 3,
        bin_type: int = BIN_NUMERICAL,
        use_missing: bool = True,
        zero_as_missing: bool = False,
        forced_bounds: Optional[Sequence[float]] = None,
        pre_filter: bool = False,
        filter_cnt: int = 0,
    ) -> "BinMapper":
        """Behavioral port of BinMapper::FindBin (reference src/io/bin.cpp:325-...).

        ``sample_values`` are the sampled non-implicit values; rows missing
        from the sample (sparse zeros) are accounted by
        ``total_sample_cnt - len(sample_values)`` extra zeros, mirroring the
        reference's sparse sampling contract.
        """
        m = cls()
        m.bin_type = bin_type
        vals = np.asarray(sample_values, dtype=np.float64)
        na_cnt = int(np.isnan(vals).sum())
        vals = vals[~np.isnan(vals)]
        implicit_zero_cnt = total_sample_cnt - len(vals) - na_cnt

        if bin_type == BIN_CATEGORICAL:
            m = cls._find_bin_categorical(m, vals, implicit_zero_cnt, max_bin,
                                          min_data_in_bin, use_missing, na_cnt)
            if not m.is_trivial and pre_filter:
                cnt_in_bin = np.asarray(m._cat_cnt_in_bin, dtype=np.int64)
                if _need_filter(cnt_in_bin, total_sample_cnt, filter_cnt,
                                BIN_CATEGORICAL):
                    m.is_trivial = True
            return m

        # resolve missing type (reference bin.cpp:351-380)
        if not use_missing:
            m.missing_type = MISSING_NONE
        elif zero_as_missing:
            m.missing_type = MISSING_ZERO
        elif na_cnt > 0:
            m.missing_type = MISSING_NAN
        else:
            m.missing_type = MISSING_NONE
        if m.missing_type != MISSING_NAN:
            # reference bin.cpp:336-352: na_cnt is only tracked in the NaN
            # branch; otherwise NaN samples fold into the implicit-zero
            # count (under zero_as_missing they ARE the missing zeros)
            implicit_zero_cnt += na_cnt
            na_cnt = 0

        if len(vals) == 0 and implicit_zero_cnt == 0:
            # all NaN
            m.bin_upper_bound = np.array([np.inf])
            m.num_bin = 2 if m.missing_type == MISSING_NAN else 1
            m.is_trivial = m.num_bin <= 1
            return m

        # distinct values with the implicit-zero splice, one-ulp merge
        # (reference bin.cpp:352-390)
        vals_sorted = np.sort(vals, kind="stable")
        distinct, counts = _distinct_with_zero(vals_sorted, implicit_zero_cnt)
        m.min_value = float(distinct[0])
        m.max_value = float(distinct[-1])

        # reference bin.cpp:395-408: the NaN missing type reserves one bin
        # and excludes the NaN count from the sample total
        if m.missing_type == MISSING_NAN:
            budget, total_eff = max_bin - 1, total_sample_cnt - na_cnt
        else:
            budget, total_eff = max_bin, total_sample_cnt
        budget = max(budget, 2)
        if forced_bounds:
            # reference bin.cpp:316-322: forced bounds switch the boundary
            # search to FindBinWithPredefinedBin
            bounds = _find_bin_with_predefined(
                distinct, counts, budget, total_eff, min_data_in_bin,
                forced_bounds)
        else:
            bounds = _find_bin_with_zero_as_one_bin(
                distinct, counts, budget, total_eff, min_data_in_bin
            )
        if m.missing_type == MISSING_ZERO and len(bounds) == 2:
            # reference bin.cpp:399-402: a 2-bin zero-as-missing feature
            # degenerates to no missing handling
            m.missing_type = MISSING_NONE
        m.bin_upper_bound = np.asarray(bounds, dtype=np.float64)
        m.num_bin = len(bounds)
        if m.missing_type == MISSING_NAN:
            m.num_bin += 1  # trailing NaN bin
        zero_total = int(counts[np.abs(distinct) <= K_ZERO_THRESHOLD].sum())
        m.sparse_rate = zero_total / max(len(vals) + implicit_zero_cnt, 1)
        m.is_trivial = m.num_bin <= 1
        if not m.is_trivial and pre_filter:
            # per-bin sample counts incl. the trailing NaN bin
            bin_of = np.searchsorted(m.bin_upper_bound, distinct, side="left")
            np.clip(bin_of, 0, len(m.bin_upper_bound) - 1, out=bin_of)
            cnt_in_bin = np.bincount(bin_of, weights=counts,
                                     minlength=m.num_bin).astype(np.int64)
            if m.missing_type == MISSING_NAN:
                cnt_in_bin[m.num_bin - 1] = na_cnt
            if _need_filter(cnt_in_bin, total_sample_cnt, filter_cnt,
                            BIN_NUMERICAL):
                m.is_trivial = True
        return m

    @staticmethod
    def _find_bin_categorical(m, vals, implicit_zero_cnt, max_bin,
                              min_data_in_bin, use_missing, na_cnt):
        # reference uses the C truncation cast for categorical values
        # (bin.cpp CategoricalBin / static_cast<int>), not rounding
        cats = np.trunc(vals).astype(np.int64)
        neg = cats < 0
        if neg.any():
            # reference warns and treats negatives as missing-ish; fold into "other"
            cats = cats[~neg]
        if implicit_zero_cnt > 0:
            cats = np.concatenate([cats, np.zeros(implicit_zero_cnt, dtype=np.int64)])
        distinct, counts = np.unique(cats, return_counts=True)
        order = np.argsort(-counts, kind="stable")
        distinct, counts = distinct[order], counts[order]
        # keep top max_bin-1 categories (reserve 1 bin for other/NaN/unseen),
        # dropping ultra-rare ones (reference uses min_data_in_bin-like cut)
        keep = min(len(distinct), max_bin - 1)
        m.bin_2_categorical = [int(c) for c in distinct[:keep]]
        m.categorical_2_bin = {int(c): i for i, c in enumerate(m.bin_2_categorical)}
        m.num_bin = keep + 1  # + other/unseen/NaN bin
        m._cat_cnt_in_bin = [int(c) for c in counts[:keep]] + [
            int(counts[keep:].sum()) + na_cnt]
        m.missing_type = MISSING_NAN if (use_missing and na_cnt > 0) else MISSING_NONE
        m.is_trivial = keep <= 1
        m.min_value = float(distinct.min()) if len(distinct) else 0.0
        m.max_value = float(distinct.max()) if len(distinct) else 0.0
        m.bin_upper_bound = np.array([np.inf])
        return m

    # ------------------------------------------------------------------
    def value_to_bin(self, values: np.ndarray) -> np.ndarray:
        """Vectorized ValueToBin (reference include/LightGBM/bin.h:457-495)."""
        v = np.asarray(values, dtype=np.float64)
        if self.bin_type == BIN_CATEGORICAL:
            out = np.full(v.shape, self.num_bin - 1, dtype=np.int32)  # other bin
            nan_mask = np.isnan(v)
            cats = np.trunc(np.where(nan_mask, -1, v)).astype(np.int64)
            for c, b in self.categorical_2_bin.items():
                out[cats == c] = b
            return out
        nan_mask = np.isnan(v)
        # NaN routed to the zero bin here; for MISSING_NAN it is overwritten
        # with the trailing NaN bin below
        v = np.where(nan_mask, 0.0, v)
        out = np.searchsorted(self.bin_upper_bound, v, side="left").astype(np.int32)
        n_real = len(self.bin_upper_bound)
        np.clip(out, 0, n_real - 1, out=out)
        if self.missing_type == MISSING_NAN:
            out[nan_mask] = self.num_bin - 1
        return out

    def bin_to_threshold(self, bin_idx: int) -> float:
        """Real-valued threshold stored in the model for a bin split
        (reference stores bin upper bound as the double threshold)."""
        n_real = len(self.bin_upper_bound)
        b = min(int(bin_idx), n_real - 1)
        ub = self.bin_upper_bound[b]
        if math.isinf(ub):
            # reference stores AvoidInf = ±1e300 (bin.cpp GetDoubleUpperBound)
            # so out-of-train-range raw values still go left at a NaN-vs-rest
            # split; max_value+1 would create train/serve skew beyond it
            ub = 1e300
        return float(ub)

    def feature_info_str(self) -> str:
        """feature_infos entry for the model text (reference gbdt_model_text.cpp)."""
        if self.is_trivial:
            return "none"
        if self.bin_type == BIN_CATEGORICAL:
            return ":".join(str(c) for c in self.bin_2_categorical)
        return f"[{self.min_value:g}:{self.max_value:g}]"

    # serialization used by the distributed bin-finding allgather
    def to_arrays(self):
        return {
            "bin_upper_bound": self.bin_upper_bound,
            "num_bin": self.num_bin,
            "missing_type": self.missing_type,
            "bin_type": self.bin_type,
            "is_trivial": self.is_trivial,
            "sparse_rate": self.sparse_rate,
            "min_value": self.min_value,
            "max_value": self.max_value,
            "bin_2_categorical": list(self.bin_2_categorical),
        }

    @classmethod
    def from_arrays(cls, d) -> "BinMapper":
        m = cls()
        m.bin_upper_bound = np.asarray(d["bin_upper_bound"], dtype=np.float64)
        m.num_bin = int(d["num_bin"])
        m.missing_type = int(d["missing_type"])
        m.bin_type = int(d["bin_type"])
        m.is_trivial = bool(d["is_trivial"])
        m.sparse_rate = float(d["sparse_rate"])
        m.min_value = float(d["min_value"])
        m.max_value = float(d["max_value"])
        m.bin_2_categorical = [int(c) for c in d.get("bin_2_categorical", [])]
        m.categorical_2_bin = {c: i for i, c in enumerate(m.bin_2_categorical)}
        return m
