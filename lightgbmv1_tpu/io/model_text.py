"""Model serialization — LightGBM v3 text format and JSON dump.

TPU-native re-implementation of the reference model text layer
(reference: ``src/boosting/gbdt_model_text.cpp`` — ``SaveModelToString``
:306-397, ``LoadModelFromString`` :410+, ``DumpModel`` :21; per-tree block
``Tree::ToString`` src/io/tree.cpp:223).

The emitted format is field-compatible with the reference (``version=v3``
header keys, per-tree ``Tree=i`` blocks, ``tree_sizes``, feature
importances, embedded parameters block) so reference tooling can read our
models and vice versa.

decision_type byte (reference include/LightGBM/tree.h decision-type masks):
bit0 = categorical, bit1 = default_left, bits 2-3 = missing type
(0 None, 1 Zero, 2 NaN).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils.log import log_fatal, log_warning
from ..io.binning import MISSING_NAN, MISSING_NONE, MISSING_ZERO
from ..models.tree import HostTree, validate_host_tree

_K_CATEGORICAL_MASK = 1
_K_DEFAULT_LEFT_MASK = 2


def _encode_decision_type(is_cat: bool, default_left: bool, missing_type: int) -> int:
    dt = 0
    if is_cat:
        dt |= _K_CATEGORICAL_MASK
    if default_left:
        dt |= _K_DEFAULT_LEFT_MASK
    dt |= (int(missing_type) & 3) << 2
    return dt


def _decode_decision_type(dt: int):
    return bool(dt & _K_CATEGORICAL_MASK), bool(dt & _K_DEFAULT_LEFT_MASK), (dt >> 2) & 3


def _fmt_float(x: float) -> str:
    """High-precision float formatting (reference Common::DoubleToStr)."""
    return np.format_float_scientific(x, precision=16, trim="-").replace("e", "e")


def _fmt_list(values, fmt=str) -> str:
    return " ".join(fmt(v) for v in values)


def _cats_to_bitset(cats: np.ndarray) -> np.ndarray:
    """Raw category values -> uint32 bitset words (reference
    Common::ConstructBitset); word count = max//32 + 1."""
    cats = np.asarray(cats, dtype=np.int64)
    if len(cats) == 0:
        return np.zeros(1, np.uint32)
    words = np.zeros(int(cats.max()) // 32 + 1, np.uint32)
    np.bitwise_or.at(words, cats // 32, np.uint32(1) << (cats % 32).astype(np.uint32))
    return words


def _bitset_to_cats(words: np.ndarray) -> np.ndarray:
    bits = np.unpackbits(np.asarray(words, np.uint32).view(np.uint8),
                         bitorder="little")
    return np.flatnonzero(bits).astype(np.int64)


def tree_to_string(tree: HostTree, index: int) -> str:
    """Per-tree block (reference: Tree::ToString, src/io/tree.cpp:223)."""
    n = tree.num_leaves
    n_nodes = max(n - 1, 0)
    is_cat = getattr(tree, "is_cat", np.zeros(n_nodes, bool))
    cat_sets = getattr(tree, "cat_sets", [None] * n_nodes)
    cat_nodes = [i for i in range(n_nodes) if is_cat[i]]
    lines = [f"Tree={index}"]
    lines.append(f"num_leaves={n}")
    lines.append(f"num_cat={len(cat_nodes)}")
    if n > 1:
        dts = [
            _encode_decision_type(bool(is_cat[i]), bool(dl), int(mt))
            for i, (dl, mt) in enumerate(zip(tree.default_left, tree.missing_type))
        ]
        # categorical nodes store their cat index in the threshold slot
        # (reference Tree::SplitCategorical, tree.cpp:78-80)
        thresholds = np.array(tree.threshold, dtype=np.float64)
        boundaries = [0]
        words_all: List[int] = []
        for ci, node in enumerate(cat_nodes):
            thresholds[node] = float(ci)
            s = cat_sets[node]
            w = _cats_to_bitset(s if s is not None else tree.cat_bins_of(node))
            boundaries.append(boundaries[-1] + len(w))
            words_all.extend(int(x) for x in w)
        lines.append("split_feature=" + _fmt_list(tree.split_feature))
        lines.append("split_gain=" + _fmt_list(tree.split_gain, lambda x: f"{x:.8g}"))
        lines.append("threshold=" + _fmt_list(thresholds, _fmt_float))
        lines.append("decision_type=" + _fmt_list(dts))
        lines.append("left_child=" + _fmt_list(tree.left_child))
        lines.append("right_child=" + _fmt_list(tree.right_child))
        lines.append("leaf_value=" + _fmt_list(tree.leaf_value, _fmt_float))
        lines.append("leaf_weight=" + _fmt_list(tree.leaf_weight, lambda x: f"{x:.8g}"))
        lines.append("leaf_count=" + _fmt_list(tree.leaf_count))
        lines.append("internal_value=" + _fmt_list(tree.internal_value, lambda x: f"{x:.8g}"))
        lines.append("internal_weight=" + _fmt_list(tree.internal_weight, lambda x: f"{x:.8g}"))
        lines.append("internal_count=" + _fmt_list(tree.internal_count))
        if cat_nodes:
            lines.append("cat_boundaries=" + _fmt_list(boundaries))
            lines.append("cat_threshold=" + _fmt_list(words_all))
    else:
        lines.append("leaf_value=" + _fmt_float(
            tree.leaf_value[0] if len(tree.leaf_value) else 0.0))
    lines.append(f"shrinkage={tree.shrinkage:g}")
    return "\n".join(lines) + "\n"


def _parse_tree_block(block: str) -> HostTree:
    kv: Dict[str, str] = {}
    index = 0
    for line in block.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("Tree="):
            index = int(line.split("=", 1)[1])
            continue
        if "=" in line:
            k, v = line.split("=", 1)
            kv[k] = v

    t = HostTree.__new__(HostTree)
    n = int(kv["num_leaves"])
    t.num_leaves = n
    t.shrinkage = float(kv.get("shrinkage", 1.0))

    def arr(key, dtype, size):
        if key not in kv or not kv[key].strip():
            return np.zeros(size, dtype=dtype)
        return np.array(kv[key].split(), dtype=dtype)

    n_nodes = max(n - 1, 0)
    t.split_feature = arr("split_feature", np.int32, n_nodes)
    t.split_gain = arr("split_gain", np.float64, n_nodes)
    t.threshold = arr("threshold", np.float64, n_nodes)
    dts = arr("decision_type", np.int32, n_nodes)
    cats, dls, mts = [], [], []
    for dt in dts:
        c, d, m = _decode_decision_type(int(dt))
        cats.append(c)
        dls.append(d)
        mts.append(m)
    t.default_left = np.array(dls, dtype=bool) if n_nodes else np.zeros(0, bool)
    t.missing_type = np.array(mts, dtype=np.int32) if n_nodes else np.zeros(0, np.int32)
    t.is_cat = np.array(cats, dtype=bool) if n_nodes else np.zeros(0, bool)
    t.cat_bitset = np.zeros((n_nodes, 1), np.uint32)   # bin-space unknown here
    t.cat_sets = [None] * n_nodes
    if t.is_cat.any():
        bounds = arr("cat_boundaries", np.int64, 0)
        words = arr("cat_threshold", np.uint32, 0)
        for node in np.flatnonzero(t.is_cat):
            ci = int(t.threshold[node])
            w = words[int(bounds[ci]): int(bounds[ci + 1])]
            t.cat_sets[node] = _bitset_to_cats(w)
    t.left_child = arr("left_child", np.int32, n_nodes)
    t.right_child = arr("right_child", np.int32, n_nodes)
    t.leaf_value = arr("leaf_value", np.float64, n)
    t.leaf_weight = arr("leaf_weight", np.float64, n)
    t.leaf_count = arr("leaf_count", np.int64, n)
    t.internal_value = arr("internal_value", np.float64, n_nodes)
    t.internal_weight = arr("internal_weight", np.float64, n_nodes)
    t.internal_count = arr("internal_count", np.int64, n_nodes)
    t.threshold_bin = np.zeros(n_nodes, np.int32)  # not stored in text
    # child-pointer structural validation (cycles, out-of-range children,
    # reconvergence): a malformed model file used to HANG the predictor's
    # ``while any(active)`` walks; fail the load instead
    try:
        validate_host_tree(t, index)
    except ValueError as e:
        log_fatal(f"Invalid model file: {e}")
    # reconstruct leaf_parent from children
    t.leaf_parent = np.full(n, -1, np.int32)
    for nd in range(n_nodes):
        for c in (t.left_child[nd], t.right_child[nd]):
            if c < 0:
                t.leaf_parent[-c - 1] = nd
    return t


@dataclass
class LoadedModel:
    """Parsed model — everything needed for prediction and continued use."""

    trees: List[HostTree] = field(default_factory=list)
    objective: str = "regression"
    objective_params: Dict[str, str] = field(default_factory=dict)
    num_class: int = 1
    num_tree_per_iteration: int = 1
    label_index: int = 0
    max_feature_idx: int = 0
    feature_names: List[str] = field(default_factory=list)
    feature_infos: List[str] = field(default_factory=list)
    average_output: bool = False
    parameters: Dict[str, str] = field(default_factory=dict)

    @property
    def num_iterations(self) -> int:
        return len(self.trees) // max(self.num_tree_per_iteration, 1)


def model_to_string(
    trees: List[HostTree],
    *,
    objective_string: str,
    num_class: int,
    num_tree_per_iteration: int,
    feature_names: List[str],
    feature_infos: List[str],
    label_index: int = 0,
    average_output: bool = False,
    parameters: Optional[Dict[str, Any]] = None,
    importance_type: int = 0,
) -> str:
    """reference: GBDT::SaveModelToString, gbdt_model_text.cpp:306-397."""
    out: List[str] = []
    out.append("tree")
    out.append("version=v3")
    out.append(f"num_class={num_class}")
    out.append(f"num_tree_per_iteration={num_tree_per_iteration}")
    out.append(f"label_index={label_index}")
    out.append(f"max_feature_idx={len(feature_names) - 1}")
    out.append(f"objective={objective_string}")
    if average_output:
        out.append("average_output")
    out.append("feature_names=" + " ".join(feature_names))
    out.append("feature_infos=" + " ".join(feature_infos))

    tree_strs = [tree_to_string(t, i) + "\n" for i, t in enumerate(trees)]
    out.append("tree_sizes=" + " ".join(str(len(s)) for s in tree_strs))
    out.append("")
    for s in tree_strs:
        out.append(s.rstrip("\n"))
        out.append("")
    out.append("end of trees")
    out.append("")

    # feature importances, descending (reference gbdt_model_text.cpp
    # FeatureImportance section; saved_feature_importance_type selects
    # split counts (0) or total gains (1) — gbdt.cpp:779-800)
    counts = np.zeros(len(feature_names), dtype=np.float64)
    for t in trees:
        for i, f in enumerate(t.split_feature[: t.num_leaves - 1]):
            counts[f] += t.split_gain[i] if importance_type == 1 else 1.0
    order = np.argsort(-counts, kind="stable")
    out.append("feature_importances:")
    for i in order:
        if counts[i] > 0:
            val = f"{counts[i]:g}" if importance_type == 1 else \
                str(int(counts[i]))
            out.append(f"{feature_names[i]}={val}")
    out.append("")
    out.append("parameters:")
    for k, v in (parameters or {}).items():
        if isinstance(v, (list, tuple)):
            v = ",".join(str(x) for x in v)
        out.append(f"[{k}: {v}]")
    out.append("end of parameters")
    out.append("")
    out.append("pandas_categorical:null")
    return "\n".join(out) + "\n"


def model_from_string(model_str: str) -> LoadedModel:
    """reference: GBDT::LoadModelFromString, gbdt_model_text.cpp:410+."""
    m = LoadedModel()
    lines = model_str.splitlines()
    i = 0
    n = len(lines)
    # header
    while i < n and not lines[i].startswith("Tree="):
        line = lines[i].strip()
        i += 1
        if not line or line == "tree":
            continue
        if line == "end of trees":
            break
        if line == "average_output":
            m.average_output = True
            continue
        if "=" not in line:
            continue
        key, value = line.split("=", 1)
        if key == "num_class":
            m.num_class = int(value)
        elif key == "num_tree_per_iteration":
            m.num_tree_per_iteration = int(value)
        elif key == "label_index":
            m.label_index = int(value)
        elif key == "max_feature_idx":
            m.max_feature_idx = int(value)
        elif key == "objective":
            parts = value.split()
            m.objective = parts[0] if parts else "regression"
            for p in parts[1:]:
                if ":" in p:
                    k2, v2 = p.split(":", 1)
                    m.objective_params[k2] = v2
        elif key == "feature_names":
            m.feature_names = value.split()
        elif key == "feature_infos":
            m.feature_infos = value.split()
    # trees
    while i < n:
        line = lines[i].strip()
        if line.startswith("Tree="):
            block = [lines[i]]
            i += 1
            while i < n and lines[i].strip() != "" :
                block.append(lines[i])
                i += 1
            m.trees.append(_parse_tree_block("\n".join(block)))
        elif line == "end of trees":
            i += 1
            break
        else:
            i += 1
    # parameters block
    in_params = False
    for j in range(i, n):
        line = lines[j].strip()
        if line == "parameters:":
            in_params = True
        elif line == "end of parameters":
            in_params = False
        elif in_params and line.startswith("[") and line.endswith("]"):
            inner = line[1:-1]
            if ": " in inner:
                k, v = inner.split(": ", 1)
                m.parameters[k] = v
    if not m.trees and "Tree=" in model_str:
        log_warning("Model parsing found no trees")
    return m


# ---------------------------------------------------------------------------
# JSON dump (reference: GBDT::DumpModel, gbdt_model_text.cpp:21-120)
# ---------------------------------------------------------------------------


def _node_to_dict(tree: HostTree, node: int, feature_names: List[str]) -> Dict:
    if node < 0:
        leaf = -node - 1
        return {
            "leaf_index": int(leaf),
            "leaf_value": float(tree.leaf_value[leaf]),
            "leaf_weight": float(tree.leaf_weight[leaf]),
            "leaf_count": int(tree.leaf_count[leaf]),
        }
    mt = {MISSING_NONE: "None", MISSING_ZERO: "Zero", MISSING_NAN: "NaN"}[
        int(tree.missing_type[node])
    ]
    return {
        "split_index": int(node),
        "split_feature": int(tree.split_feature[node]),
        "split_gain": float(tree.split_gain[node]),
        "threshold": float(tree.threshold[node]),
        "decision_type": "<=",
        "default_left": bool(tree.default_left[node]),
        "missing_type": mt,
        "internal_value": float(tree.internal_value[node]),
        "internal_weight": float(tree.internal_weight[node]),
        "internal_count": int(tree.internal_count[node]),
        "left_child": _node_to_dict(tree, int(tree.left_child[node]), feature_names),
        "right_child": _node_to_dict(tree, int(tree.right_child[node]), feature_names),
    }


def dump_model_dict(
    trees: List[HostTree],
    *,
    objective_string: str,
    num_class: int,
    num_tree_per_iteration: int,
    feature_names: List[str],
    feature_infos: List[str],
    label_index: int = 0,
    average_output: bool = False,
) -> Dict:
    return {
        "name": "tree",
        "version": "v3",
        "num_class": num_class,
        "num_tree_per_iteration": num_tree_per_iteration,
        "label_index": label_index,
        "max_feature_idx": len(feature_names) - 1,
        "objective": objective_string,
        "average_output": average_output,
        "feature_names": list(feature_names),
        "feature_infos": list(feature_infos),
        "tree_info": [
            {
                "tree_index": i,
                "num_leaves": t.num_leaves,
                "num_cat": 0,
                "shrinkage": t.shrinkage,
                "tree_structure": _node_to_dict(t, 0 if t.num_leaves > 1 else -1,
                                                feature_names),
            }
            for i, t in enumerate(trees)
        ],
    }
