from .binning import BinMapper
from .dataset import BinnedDataset, Metadata

__all__ = ["BinMapper", "BinnedDataset", "Metadata"]
