"""Crash-consistent trainer checkpoints with bit-exact resume.

The reference's recovery story is ``snapshot_freq`` model-text dumps plus
continued training via ``input_model`` (gbdt.cpp:258-262,
application.cpp:90-93).  That resume is *approximate*: the score cache is
re-seeded by predicting the loaded trees in f64, so a killed-and-resumed
run drifts from the uninterrupted one within an iteration.  For a
production trainer the bar is **bit-exact**: kill at iteration *k*,
resume, and the final model text is byte-identical to the run that never
died — otherwise every crash silently changes the model that ships.

A checkpoint bundle is therefore the FULL trainer state, not just the
model text:

* the per-tree **device arrays** in bin space (``TreeArrays`` stacked per
  field) — so DART drops, rescales and score removals replay on exactly
  the arrays the uninterrupted run holds, with no text->parse->re-bin
  roundtrip in the loop;
* the f32 **score caches** (train + every valid set) — the one piece the
  reference's predict-reseed loses;
* **RNG/bagging state**: the feature-sampling ``RandomState``, DART's
  drop ``RandomState`` + per-tree weights, and (when recorded) the
  per-iteration train-row leaf assignments the fused DART drop path
  gathers through;
* the **iteration counter**, per-tree shrink/bias metadata, CEGB masks;
* the **model text** at the checkpoint iteration — the human-visible,
  independently loadable view, and the validate-on-load surface
  (``model_from_string`` runs ``validate_host_tree`` on every tree).

File format: one zip (written via ``fileio.atomic_write_bytes`` —
tmp+fsync+rename, a crash leaves the old bundle intact) holding
``manifest.json``, ``model.txt``, optional ``base_model.txt`` (continued
training), and ``arrays.npz``.  The manifest carries SHA-256 digests of
the other members; ``load_checkpoint`` verifies them before any array is
trusted, so a torn or bit-flipped bundle is *rejected* (CheckpointError)
and the caller falls back to the previous intact one (cli.py).
"""

from __future__ import annotations

import hashlib
import io
import json
import zipfile
from typing import Any, Dict, Tuple

import numpy as np

from ..utils import fileio
from ..utils.log import log_info

FORMAT_NAME = "lightgbmv1-tpu-checkpoint"
FORMAT_VERSION = 1


class CheckpointError(RuntimeError):
    """The bundle is unreadable, torn, or inconsistent with the trainer
    it is being restored into.  Callers treat this as 'not a checkpoint'
    and fall back (previous snapshot, or fresh training)."""


def _digest(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def encode_rng_state(rng: np.random.RandomState) -> Dict[str, Any]:
    name, keys, pos, has_gauss, cached = rng.get_state()
    return {"name": name, "pos": int(pos), "has_gauss": int(has_gauss),
            "cached_gaussian": float(cached),
            "keys": np.asarray(keys, np.uint32).tolist()}


def decode_rng_state(d: Dict[str, Any]) -> tuple:
    return (d["name"], np.asarray(d["keys"], np.uint32), int(d["pos"]),
            int(d["has_gauss"]), float(d["cached_gaussian"]))


# ---------------------------------------------------------------------------
# write
# ---------------------------------------------------------------------------


def _obs_ckpt_hist(name: str, help_text: str):
    from ..obs.metrics import default_registry

    return default_registry().histogram(
        name, help_text,
        buckets=(5, 10, 20, 50, 100, 200, 500, 1000, 2000, 5000, 10000))


def write_checkpoint(path: str, manifest: Dict[str, Any],
                     arrays: Dict[str, np.ndarray], model_text: str,
                     base_model_text: str = "",
                     reference_bytes: bytes = b"") -> None:
    """Serialize and atomically write one bundle.  ``reference_bytes``
    (obs/model.ModelReference.to_bytes — the training bin-occupancy /
    score-distribution reference, ISSUE 14) rides as an optional
    digest-verified member ``reference.bin``."""
    from ..obs import trace

    t0_ns = trace.now_ns()
    _write_checkpoint_impl(path, manifest, arrays, model_text,
                           base_model_text, reference_bytes)
    ms = (trace.now_ns() - t0_ns) / 1e6
    _obs_ckpt_hist("checkpoint_save_ms",
                   "Wall time of one checkpoint-bundle write").observe(ms)
    if trace.enabled():
        trace.add_span("checkpoint.save", t0_ns, trace.now_ns() - t0_ns,
                       cat="checkpoint", args={"path": str(path)})


def _write_checkpoint_impl(path: str, manifest: Dict[str, Any],
                           arrays: Dict[str, np.ndarray], model_text: str,
                           base_model_text: str = "",
                           reference_bytes: bytes = b"") -> None:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    arrays_bytes = buf.getvalue()
    model_bytes = model_text.encode("utf-8")
    base_bytes = base_model_text.encode("utf-8") if base_model_text else b""

    manifest = dict(manifest)
    manifest["format"] = FORMAT_NAME
    manifest["format_version"] = FORMAT_VERSION
    manifest["digests"] = {
        "arrays.npz": _digest(arrays_bytes),
        "model.txt": _digest(model_bytes),
    }
    if base_bytes:
        manifest["digests"]["base_model.txt"] = _digest(base_bytes)
    if reference_bytes:
        manifest["digests"]["reference.bin"] = _digest(reference_bytes)
    out = io.BytesIO()
    # ZIP_STORED: the payload is already compact npz; the checkpoint write
    # sits on the training path, so cheap beats small
    with zipfile.ZipFile(out, "w", zipfile.ZIP_STORED) as zf:
        zf.writestr("manifest.json", json.dumps(manifest))
        zf.writestr("model.txt", model_bytes)
        if base_bytes:
            zf.writestr("base_model.txt", base_bytes)
        zf.writestr("arrays.npz", arrays_bytes)
        if reference_bytes:
            zf.writestr("reference.bin", reference_bytes)
    fileio.atomic_write_bytes(path, out.getvalue(), site=path)


# ---------------------------------------------------------------------------
# read / validate
# ---------------------------------------------------------------------------


def is_checkpoint_file(path) -> bool:
    """Cheap sniff: a zip whose member list starts with our manifest."""
    try:
        with fileio.open_file(str(path), "rb") as fh:
            head = fh.read(4)
        if head[:2] != b"PK":
            return False
        with fileio.open_file(str(path), "rb") as fh:
            with zipfile.ZipFile(io.BytesIO(fh.read())) as zf:
                return "manifest.json" in zf.namelist()
    except Exception:  # noqa: BLE001 — any unreadable file is "not a ckpt"
        return False


def load_checkpoint(path: str) -> Dict[str, Any]:
    """Read + fully validate a bundle.  Raises :class:`CheckpointError`
    on ANY integrity failure — torn zip, digest mismatch, missing
    members, or model text whose trees fail ``validate_host_tree``.

    Returns ``{"manifest", "arrays", "model_text", "base_model_text"}``.
    """
    from ..obs import trace

    t0_ns = trace.now_ns()
    out = _load_checkpoint_impl(path)
    ms = (trace.now_ns() - t0_ns) / 1e6
    _obs_ckpt_hist("checkpoint_load_ms",
                   "Wall time of one validated checkpoint load").observe(ms)
    if trace.enabled():
        trace.add_span("checkpoint.load", t0_ns, trace.now_ns() - t0_ns,
                       cat="checkpoint", args={"path": str(path)})
    return out


def _load_checkpoint_impl(path: str) -> Dict[str, Any]:
    try:
        with fileio.open_file(str(path), "rb") as fh:
            raw = fh.read()
        with zipfile.ZipFile(io.BytesIO(raw)) as zf:
            names = set(zf.namelist())
            if "manifest.json" not in names:
                raise CheckpointError(f"{path}: no manifest")
            manifest = json.loads(zf.read("manifest.json"))
            if manifest.get("format") != FORMAT_NAME:
                raise CheckpointError(f"{path}: not a {FORMAT_NAME} bundle")
            members = {}
            for member, want in manifest.get("digests", {}).items():
                if member not in names:
                    raise CheckpointError(f"{path}: missing {member}")
                data = zf.read(member)
                if _digest(data) != want:
                    raise CheckpointError(
                        f"{path}: digest mismatch on {member} (torn or "
                        "corrupted bundle)")
                members[member] = data
    except CheckpointError:
        raise
    except Exception as e:  # noqa: BLE001 — zip/json/IO failures
        raise CheckpointError(
            f"{path}: unreadable checkpoint ({type(e).__name__}: {e})")

    model_text = members.get("model.txt", b"").decode("utf-8")
    base_text = members.get("base_model.txt", b"").decode("utf-8")
    # validate-on-load rides PR 4's validate_host_tree (model_from_string
    # runs it per tree): a structurally invalid model can never resume
    try:
        from .model_text import model_from_string

        loaded = model_from_string(model_text)
    except Exception as e:  # noqa: BLE001
        raise CheckpointError(
            f"{path}: model text failed validation "
            f"({type(e).__name__}: {e})")
    if len(loaded.trees) != int(manifest.get("num_trees_total",
                                             len(loaded.trees))):
        raise CheckpointError(
            f"{path}: manifest claims {manifest.get('num_trees_total')} "
            f"trees, model text carries {len(loaded.trees)}")

    try:
        npz = np.load(io.BytesIO(members["arrays.npz"]), allow_pickle=False)
        arrays = {k: npz[k] for k in npz.files}
    except Exception as e:  # noqa: BLE001
        raise CheckpointError(
            f"{path}: unreadable arrays ({type(e).__name__}: {e})")
    # a NaN-poisoned trainer must not be able to produce a "valid"
    # checkpoint: score caches are required finite (tree arrays are not
    # checked — real thresholds may legitimately carry +inf bin uppers)
    for k, a in arrays.items():
        if k.endswith("_score") or "_score_" in k:
            if a.dtype.kind == "f" and not np.isfinite(a).all():
                raise CheckpointError(f"{path}: non-finite values in {k}")
    return {"manifest": manifest, "arrays": arrays,
            "model_text": model_text, "base_model_text": base_text,
            # training reference (obs/model.py; digest already verified
            # via the manifest sweep above) — empty for pre-ISSUE-14
            # bundles, which load unchanged
            "reference_bytes": members.get("reference.bin", b"")}


def validate_checkpoint(path: str) -> Dict[str, Any]:
    """Full validation pass; returns the manifest.  Used by the CLI's
    resume-point scan to pick the newest INTACT bundle."""
    return load_checkpoint(path)["manifest"]


def checkpoint_iteration(path: str) -> int:
    return int(validate_checkpoint(path)["iteration"])


def log_loaded(path: str, manifest: Dict[str, Any]) -> None:
    log_info(
        f"Loaded checkpoint {path}: iteration {manifest.get('iteration')}, "
        f"{manifest.get('num_trees')} trees, "
        f"boosting={manifest.get('boosting')}")
