"""Exclusive Feature Bundling (EFB) — TPU-first densification.

Re-design of the reference's FeatureGroup/EFB machinery
(reference: ``src/io/dataset.cpp:41-235`` — ``GetConflictCount`` :50,
``FindGroups`` :97, ``FastFeatureBundling`` :236;
``include/LightGBM/feature_group.h:21`` FeatureGroup with per-feature bin
offsets).  Mutually-exclusive sparse features (rarely nonzero on the same
row) are packed into one dense *bundle* column, so the histogram pass —
the hot loop — runs over ``num_bundles`` columns instead of
``num_features``.  On TPU this is exactly what the MXU wants: thousands of
mostly-zero columns become a handful of dense ones, and the binned-matrix
HBM footprint drops proportionally.

Differences from the reference's encoding (simplicity over slot packing):

* Bundle bin 0 means "every member feature at its zero bin"; member ``f``
  with a non-zero bin ``b`` maps to ``offset_f + b``.  The reference elides
  each feature's most-frequent bin from its range
  (``feature_group.h:36-48``); here members keep their full bin range, so
  one slot per member (its zero bin) is unused — the per-feature histogram
  view is then a pure slice, and the zero-bin count is recovered from the
  parent totals exactly like the reference's ``FixHistogram``
  (``src/io/dataset.cpp:1410``).
* The model is untouched: trees always record ORIGINAL feature indices and
  thresholds in original bin space; bundling is invisible outside training
  (same property as the reference).

The greedy conflict-count grouping follows the reference/EFB paper: order
features by non-zero count descending, first-fit into the bundle whose
conflict count stays within budget, subject to the uint8 bin-capacity cap.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from ..utils.log import log_info, log_warning

MAX_BUNDLE_BINS = 256      # uint8 bundles only — the Pallas kernel's domain
_CONFLICT_SAMPLE = 32768   # rows sampled for conflict counting


@dataclass
class BundleLayout:
    """Mapping between original features and bundle columns.

    bundle_of:   (F,) int32 — bundle column of each original feature
    offset:      (F,) int32 — bin offset of the feature inside its bundle
                  (0 for singleton bundles: bundle bin == original bin)
    is_bundled:  (F,) bool  — True when the feature shares a bundle (its
                  zero-bin count must be recovered from parent totals)
    bundle_nbins:(BF,) int32 — total bins of each bundle column
    """

    bundle_of: np.ndarray
    offset: np.ndarray
    is_bundled: np.ndarray
    bundle_nbins: np.ndarray

    @property
    def num_bundles(self) -> int:
        return len(self.bundle_nbins)

    @property
    def num_features(self) -> int:
        return len(self.bundle_of)


def find_bundles(
    nonzero_masks: np.ndarray,      # (F, S) bool — sampled rows, bin != zero_bin
    num_bins: Sequence[int],        # (F,) per-feature bin counts
    max_conflict_rate: float = 0.0,
    max_bundle_bins: int = MAX_BUNDLE_BINS,
) -> Optional[BundleLayout]:
    """Greedy conflict-bounded grouping (reference ``FindGroups``,
    src/io/dataset.cpp:97-235).  Returns None when bundling would not
    reduce the column count (e.g. all-dense data)."""
    F, S = nonzero_masks.shape
    num_bins = np.asarray(num_bins, dtype=np.int64)
    budget = int(max_conflict_rate * S)

    order = np.argsort(-nonzero_masks.sum(axis=1, dtype=np.int64),
                       kind="stable")
    group_masks: List[np.ndarray] = []       # aggregated nonzero per bundle
    group_conflicts: List[int] = []          # conflicts spent per bundle
    group_bins: List[int] = []               # bins used (incl. shared bin 0)
    group_members: List[List[int]] = []

    # bounded search, like the reference's max_search_group random fallback
    # (dataset.cpp:119-130): without a cap the greedy loop is
    # O(F * groups * S) and stalls on 100k-feature inputs
    MAX_SEARCH = 256
    rng = np.random.RandomState(3)

    for f in order:
        fm = nonzero_masks[f]
        nb = int(num_bins[f])
        placed = False
        n_groups = len(group_masks)
        if n_groups <= MAX_SEARCH:
            candidates = range(n_groups)
        else:
            candidates = rng.choice(n_groups, size=MAX_SEARCH, replace=False)
        for g in candidates:
            # (reference GetConflictCount, dataset.cpp:50): rows where both
            # the bundle and the candidate are non-zero
            if group_bins[g] + nb > max_bundle_bins:
                continue
            cnt = int(np.count_nonzero(group_masks[g] & fm))
            if group_conflicts[g] + cnt <= budget:
                group_masks[g] |= fm
                group_conflicts[g] += cnt
                group_bins[g] += nb
                group_members[g].append(int(f))
                placed = True
                break
        if not placed:
            group_masks.append(fm.copy())
            group_conflicts.append(0)
            # +1: bundle bin 0 is the shared all-zero slot
            group_bins.append(1 + nb)
            group_members.append([int(f)])

    BF = len(group_members)
    if BF >= F:
        return None

    bundle_of = np.zeros(F, np.int32)
    offset = np.zeros(F, np.int32)
    is_bundled = np.zeros(F, bool)
    bundle_nbins = np.zeros(BF, np.int32)
    for g, members in enumerate(group_members):
        if len(members) == 1:
            f = members[0]
            bundle_of[f] = g
            offset[f] = 0                      # identity: bin == bundle bin
            bundle_nbins[g] = num_bins[f]
        else:
            off = 1                            # bin 0 = all members zero
            for f in members:
                bundle_of[f] = g
                offset[f] = off
                is_bundled[f] = True
                off += int(num_bins[f])
            bundle_nbins[g] = off
    return BundleLayout(bundle_of, offset, is_bundled, bundle_nbins)


def conflict_masks_from_dense(
    binned: np.ndarray,             # (F, N)
    zero_bins: Sequence[int],
    sample_cnt: int = _CONFLICT_SAMPLE,
    seed: int = 1,
) -> np.ndarray:
    """(F, S) bool sampled non-zero masks from a dense binned matrix."""
    F, N = binned.shape
    rng = np.random.RandomState(seed)
    if N > sample_cnt:
        idx = rng.choice(N, size=sample_cnt, replace=False)
        sub = binned[:, idx]
    else:
        sub = binned
    zb = np.asarray(zero_bins, dtype=binned.dtype)[:, None]
    return sub != zb


def apply_bundles_dense(binned: np.ndarray, zero_bins: Sequence[int],
                        layout: BundleLayout) -> np.ndarray:
    """(F, N) -> (BF, N) bundled matrix.  Conflicting rows (two members
    non-zero — possible when max_conflict_rate > 0) keep the LAST member's
    value, mirroring the reference's push-order overwrite."""
    F, N = binned.shape
    dtype = np.uint8 if int(layout.bundle_nbins.max()) <= 256 else np.int16
    out = np.zeros((layout.num_bundles, N), dtype=dtype)
    zb = np.asarray(zero_bins)
    for f in range(F):
        g = int(layout.bundle_of[f])
        if not layout.is_bundled[f]:
            out[g] = binned[f].astype(dtype)
            continue
        nz = binned[f] != zb[f]
        out[g][nz] = (layout.offset[f] + binned[f][nz]).astype(dtype)
    return out


def apply_bundles_csr(
    indptr: np.ndarray, indices: np.ndarray, bin_values: np.ndarray,
    num_data: int, zero_bins: Sequence[int], layout: BundleLayout,
) -> np.ndarray:
    """Build the (BF, N) bundled matrix straight from binned CSR triplets
    (row-compressed; ``bin_values`` are already ORIGINAL bin indices) —
    the wide-sparse input path never materializes the dense (F, N) matrix
    (reference analog: sparse push into FeatureGroup bins,
    dataset_loader.cpp:1003-1100)."""
    dtype = np.uint8 if int(layout.bundle_nbins.max()) <= 256 else np.int16
    out = np.zeros((layout.num_bundles, num_data), dtype=dtype)
    zb = np.asarray(zero_bins)
    # absent CSR entries mean raw 0.0: bundle bin 0 for bundled members, but
    # the feature's zero_bin for singleton bundles
    for f in np.where(~layout.is_bundled)[0]:
        if zb[f] != 0:
            out[int(layout.bundle_of[f])][:] = zb[f]
    rows = np.repeat(np.arange(num_data), np.diff(indptr))
    feats = indices
    nz = bin_values != zb[feats]
    bundle_bin = np.where(layout.is_bundled[feats],
                          layout.offset[feats] + bin_values,
                          bin_values)
    # bundled members write only their non-zero bins; singletons write every
    # explicit entry (including explicit zeros, already equal to zero_bin)
    w = nz | (~layout.is_bundled[feats])
    out[layout.bundle_of[feats[w]], rows[w]] = bundle_bin[w].astype(dtype)
    return out


class BundleArrays:
    """Device-resident layout arrays consumed by jitted code."""

    def __init__(self, layout: BundleLayout, zero_bins, num_bins):
        import jax.numpy as jnp

        self.bundle_of = jnp.asarray(layout.bundle_of, jnp.int32)
        self.offset = jnp.asarray(layout.offset, jnp.int32)
        self.is_bundled = jnp.asarray(layout.is_bundled)
        self.zero_bin = jnp.asarray(np.asarray(zero_bins), jnp.int32)
        self.num_bins = jnp.asarray(np.asarray(num_bins), jnp.int32)


def expand_bundle_hist(hist_b, parent_sum, ba: BundleArrays, num_bins: int):
    """(BF, Bb, 3) bundle histogram -> (F, B, 3) per-original-feature view.

    Each feature's non-zero bins are a slice of its bundle's histogram; the
    zero-bin count of a bundled feature is recovered from the parent totals
    (the analog of the reference's most-freq-bin recovery ``FixHistogram``,
    src/io/dataset.cpp:1410).  Singleton bundles are identity slices, so
    unbundled features see exactly the histograms they would without EFB.
    """
    import jax.numpy as jnp

    Bb = hist_b.shape[1]
    B = num_bins
    F = ba.bundle_of.shape[0]
    bins_iota = jnp.arange(B, dtype=jnp.int32)
    idx = ba.offset[:, None] + bins_iota[None, :]                # (F, B)
    v = hist_b[ba.bundle_of[:, None], jnp.clip(idx, 0, Bb - 1)]  # (F, B, 3)
    valid = (bins_iota[None, :] < ba.num_bins[:, None]) & (idx < Bb)
    v = jnp.where(valid[..., None], v, 0.0)
    zfix = parent_sum[None, :] - v.sum(axis=1)                   # (F, 3)
    zb = jnp.clip(ba.zero_bin, 0, B - 1)
    cur = v[jnp.arange(F), zb]                                   # (F, 3)
    newz = jnp.where(ba.is_bundled[:, None], zfix, cur)
    return v.at[jnp.arange(F), zb].set(newz)


def bundle_bins_of_feat(bundled, feat, ba: BundleArrays):
    """(BF, N) bundled matrix -> (N,) ORIGINAL bins of feature ``feat``
    (traced scalar).  Rows outside the feature's bundle range are at the
    feature's zero bin."""
    import jax.numpy as jnp

    bb = bundled[ba.bundle_of[feat]].astype(jnp.int32)           # (N,)
    inner = bb - ba.offset[feat]
    in_range = (inner >= 0) & (inner < ba.num_bins[feat])
    mapped = jnp.where(in_range, inner, ba.zero_bin[feat])
    return jnp.where(ba.is_bundled[feat], mapped, bb)


def bundle_bins_of_rows(bundled, f_row, ba: BundleArrays):
    """Per-row feature variant: ``f_row`` (N,) -> (N,) original bins (the
    level-wise grower's decision pass)."""
    import jax.numpy as jnp

    g_row = ba.bundle_of[f_row]                                   # (N,)
    bb = jnp.take_along_axis(bundled, g_row[None, :], axis=0)[0] \
        .astype(jnp.int32)
    off = ba.offset[f_row]
    inner = bb - off
    in_range = (inner >= 0) & (inner < ba.num_bins[f_row])
    mapped = jnp.where(in_range, inner, ba.zero_bin[f_row])
    return jnp.where(ba.is_bundled[f_row], mapped, bb)


def maybe_bundle(binned: np.ndarray, zero_bins, num_bins,
                 max_conflict_rate: float = 0.0,
                 min_saving: float = 0.2):
    """Decide + build bundles for a dense binned matrix.  Returns
    ``(bundled, layout)`` or ``(binned, None)`` when bundling saves less
    than ``min_saving`` of the columns (reference gates EFB behind
    ``enable_bundle``; all-dense data naturally yields no groups)."""
    F = binned.shape[0]
    if F < 3:
        return binned, None
    masks = conflict_masks_from_dense(binned, zero_bins)
    layout = find_bundles(masks, num_bins,
                          max_conflict_rate=max_conflict_rate)
    if layout is None or layout.num_bundles > F * (1.0 - min_saving):
        return binned, None
    bundled = apply_bundles_dense(binned, zero_bins, layout)
    log_info(f"EFB: bundled {F} features into {layout.num_bundles} dense "
             f"columns (max {int(layout.bundle_nbins.max())} bins/bundle)")
    return bundled, layout
