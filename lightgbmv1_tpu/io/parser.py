"""Data file parsers: CSV / TSV / LibSVM with auto format detection.

TPU-native replacement for the reference parsers (reference:
``src/io/parser.cpp`` ``Parser::CreateParser`` auto-detection,
``CSVParser``/``TSVParser``/``LibSVMParser``; loader conventions from
``src/io/dataset_loader.cpp`` — label/weight/group columns, sibling
``<file>.weight`` / ``<file>.query`` files, ``#`` comments, optional
header).

A native C++ fast path lives in ``native/`` (ctypes-loaded when built);
this module is the always-available numpy fallback and the semantics
reference.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from ..utils import fileio
from ..utils.log import log_fatal, log_info, log_warning


def _detect_format(sample_lines: List[str]) -> str:
    """reference: Parser::CreateParser auto-detection logic."""
    for line in sample_lines:
        if ":" in line.split("#", 1)[0]:
            tokens = line.split()
            # libsvm if any token beyond the first looks like idx:value
            for tok in tokens[1:]:
                if ":" in tok:
                    head = tok.split(":", 1)[0]
                    try:
                        int(head)
                        return "libsvm"
                    except ValueError:
                        break
    first = sample_lines[0] if sample_lines else ""
    if "\t" in first:
        return "tsv"
    if "," in first:
        return "csv"
    return "tsv"  # whitespace-separated


# missing-value spellings accepted by the reference's Atof path
# (reference: include/LightGBM/utils/common.h Atof "na"/"nan"/"null" handling)
_MISS_TOKENS = frozenset(("", "na", "nan", "NA", "NaN", "null"))


def _fval(tok: str) -> float:
    return float(tok) if tok not in _MISS_TOKENS else np.nan


def _parse_dense(lines: List[str], sep: Optional[str]) -> np.ndarray:
    rows = []
    for line in lines:
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        parts = line.split(sep) if sep else line.split()
        rows.append([_fval(p) for p in parts])
    return np.asarray(rows, dtype=np.float64)


def _parse_libsvm(lines: List[str]) -> Tuple[np.ndarray, np.ndarray]:
    labels = []
    entries = []  # (row, idx, val)
    max_idx = -1
    for r, line in enumerate(lines):
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        toks = line.split()
        labels.append(float(toks[0]))
        row = len(labels) - 1
        for tok in toks[1:]:
            if ":" not in tok:
                continue
            i, v = tok.split(":", 1)
            idx = int(i)
            max_idx = max(max_idx, idx)
            entries.append((row, idx, float(v)))
    X = np.zeros((len(labels), max_idx + 1), dtype=np.float64)
    for r, c, v in entries:
        X[r, c] = v
    return X, np.asarray(labels)


class DataFile:
    """Parsed data file: features + label/weight/group metadata."""

    def __init__(self, X, label=None, weight=None, group=None,
                 feature_names=None):
        self.X = X
        self.label = label
        self.weight = weight
        self.group = group
        self.feature_names = feature_names


def _resolve_column(spec: str, header_names: Optional[List[str]], what: str) -> Optional[int]:
    """Column spec: int index, or ``name:<colname>`` with header
    (reference: config label_column conventions)."""
    if spec == "":
        return None
    if spec.startswith("name:"):
        name = spec[5:]
        if not header_names:
            log_fatal(f"{what} column by name requires header=true")
        if name not in header_names:
            log_fatal(f"{what} column {name} not found in header")
        return header_names.index(name)
    return int(spec)


def shard_rows(num_rows: int, rank: int, world: int):
    """Contiguous row range for one rank (reference loader pre-partition,
    dataset_loader.cpp:167). Single definition shared with parallel/."""
    per = -(-num_rows // world)
    lo = min(rank * per, num_rows)
    hi = min(lo + per, num_rows)
    return lo, hi


def load_two_round(path: str, config, categorical_features=None):
    """Two-pass streaming loader (``two_round=true``; reference:
    DatasetLoader::LoadFromFile's two-round branch, src/io/dataset_loader.cpp
    :208-235, and ``ExtractFeaturesFromFile`` :1101-1160).

    Pass 1 streams the file once, reservoir-sampling
    ``bin_construct_sample_cnt`` rows for bin-mapper construction while
    collecting the (small) label/weight/group columns; pass 2 re-reads the
    file in chunks and bins rows straight into the ``(F, N)`` bin matrix.
    Peak memory is the binned matrix (1 byte/value) plus one chunk — the
    raw float64 matrix (8 bytes/value) is never materialized, which is the
    reference's exact speed-for-memory trade.

    Returns a ``BinnedDataset`` or ``None`` when the format has no
    streaming path (libsvm), in which case the caller should fall back to
    the in-memory loader.
    """
    from .binning import BIN_CATEGORICAL, BIN_NUMERICAL, BinMapper, \
        get_forced_bins
    from .dataset import BinnedDataset, Metadata

    if not fileio.exists(path):
        log_fatal(f"Data file {path} does not exist")
    with fileio.open_file(path) as fh:
        head = [fh.readline().rstrip("\n") for _ in range(24)]
    header_names = None
    head_data = list(head)
    if config.header and head:
        first = head[0]
        hsep = "\t" if "\t" in first else ("," if "," in first else None)
        header_names = first.split(hsep) if hsep else first.split()
        head_data = head[1:]
    fmt = _detect_format([ln for ln in head_data if ln.strip()][:20])
    if fmt == "libsvm":
        log_warning("two_round loading has no libsvm streaming path; "
                    "falling back to the in-memory loader")
        return None
    first_data = next((ln for ln in head_data if ln.strip()), "")
    sep = "\t" if fmt == "tsv" and "\t" in first_data else (
        "," if fmt == "csv" else None)

    label_idx = _resolve_column(config.label_column, header_names, "label")
    if label_idx is None:
        label_idx = 0
    weight_idx = _resolve_column(config.weight_column, header_names, "weight")
    group_idx = _resolve_column(config.group_column, header_names, "group")
    ignore = set()
    if config.ignore_column:
        for tok in config.ignore_column.split(","):
            idx = _resolve_column(tok, header_names, "ignore")
            if idx is not None:
                ignore.add(idx)

    def parse_row(line):
        parts = line.split(sep) if sep else line.split()
        return [_fval(p) for p in parts]

    # ---- pass 1: metadata columns + reservoir sample for binning ---------
    rng = np.random.RandomState(config.data_random_seed)
    cap = max(1, config.bin_construct_sample_cnt)
    sample_rows: List[list] = []
    label_l, weight_l, group_l = [], [], []
    n_rows = 0
    fval = _fval

    with fileio.open_file(path) as fh:
        if config.header:
            fh.readline()
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            # only the metadata columns are float-parsed per row; the full
            # row is converted only when it enters the reservoir
            parts = line.split(sep) if sep else line.split()
            if label_idx is not None:
                label_l.append(fval(parts[label_idx]))
            if weight_idx is not None:
                weight_l.append(fval(parts[weight_idx]))
            if group_idx is not None:
                group_l.append(fval(parts[group_idx]))
            # reservoir sampling (uniform over all rows, one pass)
            if n_rows < cap:
                sample_rows.append([fval(p) for p in parts])
            else:
                j = rng.randint(0, n_rows + 1)
                if j < cap:
                    sample_rows[j] = [fval(p) for p in parts]
            n_rows += 1
    if n_rows == 0:
        log_fatal(f"Data file {path} is empty")

    meta_cols = {c for c in (label_idx, weight_idx, group_idx)
                 if c is not None}
    ncol = len(sample_rows[0])
    keep = [c for c in range(ncol) if c not in meta_cols and c not in ignore]
    num_features = len(keep)
    feature_names = ([header_names[c] for c in keep] if header_names
                     else None)
    categorical = set(categorical_features or [])

    sample_mat = np.asarray(sample_rows, np.float64)[:, keep]
    sample_cnt = sample_mat.shape[0]
    max_bins = list(config.max_bin_by_feature) or \
        [config.max_bin] * num_features
    if len(max_bins) != num_features:
        log_fatal("max_bin_by_feature length must equal number of features")
    forced = get_forced_bins(config.forcedbins_filename, num_features,
                             categorical)
    mappers = [
        BinMapper.find_bin(
            sample_mat[:, j],
            total_sample_cnt=sample_cnt,
            max_bin=max_bins[j],
            min_data_in_bin=config.min_data_in_bin,
            bin_type=BIN_CATEGORICAL if j in categorical else BIN_NUMERICAL,
            use_missing=config.use_missing,
            zero_as_missing=config.zero_as_missing,
            forced_bounds=forced[j],
            pre_filter=config.feature_pre_filter,
            filter_cnt=int(config.min_data_in_leaf * sample_cnt
                           / max(n_rows, 1)),
        )
        for j in range(num_features)
    ]

    # ---- pass 2: chunked re-read, binned in place ------------------------
    max_nb = max(m.num_bin for m in mappers) if mappers else 2
    dtype = np.uint8 if max_nb <= 256 else np.int16
    binned = np.empty((num_features, n_rows), dtype=dtype)
    CHUNK = 65536
    lo = 0
    buf: List[list] = []

    def flush():
        nonlocal lo
        if not buf:
            return
        chunk = np.asarray(buf, np.float64)[:, keep]     # (rows, F)
        for j, m in enumerate(mappers):
            binned[j, lo:lo + len(buf)] = m.value_to_bin(
                chunk[:, j]).astype(dtype)
        lo += len(buf)
        buf.clear()

    with fileio.open_file(path) as fh:
        if config.header:
            fh.readline()
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            buf.append(parse_row(line))
            if len(buf) >= CHUNK:
                flush()
        flush()

    meta = Metadata()
    meta.label = np.asarray(label_l, np.float32)
    if weight_idx is not None:
        meta.weight = np.asarray(weight_l, np.float32)
    wfile = path + ".weight"
    if meta.weight is None and os.path.exists(wfile):
        meta.weight = np.loadtxt(wfile, dtype=np.float64,
                                 ndmin=1).astype(np.float32)
    group = None
    if group_idx is not None:
        qid = np.asarray(group_l)
        change = np.flatnonzero(np.diff(qid) != 0)
        bounds = np.concatenate([[0], change + 1, [len(qid)]])
        group = np.diff(bounds)
    qfile = path + ".query"
    if group is None and os.path.exists(qfile):
        group = np.loadtxt(qfile, dtype=np.int64, ndmin=1)
    meta.set_group(group)
    # explicit initscore_filename overrides the .init sibling convention
    ifile = config.initscore_filename or (path + ".init")
    if os.path.exists(ifile):
        meta.init_score = np.loadtxt(ifile, dtype=np.float64)

    ds = BinnedDataset(binned, mappers, meta, feature_names,
                       max_bin=config.max_bin)
    log_info(f"two_round: streamed {n_rows} rows x {num_features} features "
             f"in two passes ({binned.nbytes >> 20} MB binned)")
    return ds


def load_data_file(
    path: str,
    *,
    has_header: bool = False,
    label_column: str = "",
    weight_column: str = "",
    group_column: str = "",
    ignore_column: str = "",
    is_predict: bool = False,
    rank: Optional[int] = None,
    num_machines: int = 1,
    num_threads: int = 0,
    init_score_file: str = "",
) -> DataFile:
    """Load a training/prediction data file with the reference's loader
    conventions (reference: DatasetLoader::LoadFromFile,
    src/io/dataset_loader.cpp:167; sibling weight/query files
    metadata.cpp conventions).

    ``rank``/``num_machines``: parse only this rank's contiguous row shard
    (the reference's loader-level pre-partition). Only the owned lines are
    tokenized/parsed; the raw text is still read once to index lines."""
    if not fileio.exists(path):
        log_fatal(f"Data file {path} does not exist")
    # read only a head sample first: format detection + header names need a
    # few lines, and the native fast path reads the file itself (avoiding a
    # second full read + full Python line list on the fast path)
    with fileio.open_file(path) as fh:
        head = [fh.readline().rstrip("\n") for _ in range(24)]
    header_names = None
    head_data = list(head)
    if has_header and head:
        first = head[0]
        sep = "\t" if "\t" in first else ("," if "," in first else None)
        header_names = first.split(sep) if sep else first.split()
        head_data = head[1:]

    fmt = _detect_format([ln for ln in head_data if ln.strip()][:20])
    lines = None
    sharded = rank is not None and num_machines > 1
    shard_range = [0, None]

    def all_lines():
        nonlocal lines
        if lines is None:
            with fileio.open_file(path) as fh:
                lines = fh.read().splitlines()
            if has_header and lines:
                lines = lines[1:]
            if sharded:
                # keep only this rank's contiguous data-line shard; only
                # those lines get tokenized below
                data_idx = [i for i, ln in enumerate(lines)
                            if ln.split("#", 1)[0].strip()]
                lo, hi = shard_rows(len(data_idx), rank, num_machines)
                shard_range[0], shard_range[1] = lo, hi
                lines = [lines[i] for i in data_idx[lo:hi]]
        return lines

    label = weight = group = None
    if fmt == "libsvm":
        if sharded:
            # a shard's max feature index need not match other ranks';
            # consistent distributed libsvm loading needs a global
            # max-index pass, which is not implemented
            log_fatal("rank-sharded loading of libsvm files is not "
                      "supported; use a dense format or pre-partitioned "
                      "files")
        X, label = _parse_libsvm(all_lines())
        feature_names = None
    else:
        first_data = next((ln for ln in head_data if ln.strip()), "")
        sep = "\t" if fmt == "tsv" and "\t" in first_data else (
            "," if fmt == "csv" else None)
        # native C++ fast path (native/text_parser.cpp, multithreaded);
        # the Python parser is the semantics reference and the fallback
        # (sharded loads parse only the owned lines, Python path)
        from ..native import parse_dense_file

        data = None if (sharded or fileio.is_remote_path(path)) else \
            parse_dense_file(path, has_header, sep, num_threads)
        if data is None:
            data = _parse_dense(all_lines(), sep)
        label_idx = _resolve_column(label_column, header_names, "label")
        if label_idx is None:
            label_idx = 0 if not is_predict else None
        weight_idx = _resolve_column(weight_column, header_names, "weight")
        group_idx = _resolve_column(group_column, header_names, "group")
        ignore = set()
        if ignore_column:
            for tok in ignore_column.split(","):
                idx = _resolve_column(tok, header_names, "ignore")
                if idx is not None:
                    ignore.add(idx)
        meta_cols = {c for c in (label_idx, weight_idx, group_idx) if c is not None}
        keep = [c for c in range(data.shape[1])
                if c not in meta_cols and c not in ignore]
        X = data[:, keep]
        feature_names = (
            [header_names[c] for c in keep] if header_names else None
        )
        if label_idx is not None:
            label = data[:, label_idx]
        if weight_idx is not None:
            weight = data[:, weight_idx]
        if group_idx is not None:
            if sharded:
                log_warning(
                    "group_column with rank-sharded loading: queries that "
                    "straddle a shard boundary are split across ranks "
                    "(query-aligned sharding is not implemented)")
            # group column holds a query id per row -> convert to sizes
            qid = data[:, group_idx]
            change = np.flatnonzero(np.diff(qid) != 0)
            bounds = np.concatenate([[0], change + 1, [len(qid)]])
            group = np.diff(bounds)

    # sibling files (reference: metadata loads <data>.weight / <data>.query)
    wfile = path + ".weight"
    if weight is None and os.path.exists(wfile):
        weight = np.loadtxt(wfile, dtype=np.float64, ndmin=1)
        if sharded:
            weight = weight[shard_range[0]:shard_range[1]]
        log_info(f"Loading weights from {wfile}")
    qfile = path + ".query"
    if group is None and os.path.exists(qfile):
        if sharded:
            log_warning("query boundaries + rank-sharded loading need "
                        "query-aligned shards, which is not implemented; "
                        "ignoring the .query sibling")
        else:
            group = np.loadtxt(qfile, dtype=np.int64, ndmin=1)
            log_info(f"Loading query boundaries from {qfile}")
    # explicit initscore_filename overrides the .init sibling convention
    # (reference: config.h initscore_filename, metadata.cpp LoadInitialScore)
    ifile = init_score_file or (path + ".init")
    init_score = None
    if os.path.exists(ifile):
        init_score = np.loadtxt(ifile, dtype=np.float64)
        if sharded:
            init_score = init_score[shard_range[0]:shard_range[1]]
        log_info(f"Loading initial scores from {ifile}")

    df = DataFile(X, label, weight, group, feature_names)
    df.init_score = init_score
    return df
