"""Binned dataset: the device-resident training representation.

TPU-native re-design of the reference Dataset/Metadata
(reference: ``include/LightGBM/dataset.h:332-713`` class Dataset,
``dataset.h:40-248`` class Metadata, ``src/io/dataset.cpp``).

Representation decisions (SURVEY.md §7):

* Binned matrix lives in HBM as ``(num_features, num_data)`` integer bins
  (uint8 when max bin count <= 256 else int16 — the analog of the reference's
  ``DenseBin<uint8_t>/DenseBin<uint16_t>`` family, src/io/dense_bin.hpp:52).
  There are no feature groups, no EFB, no sparse bins: density is what the
  MXU wants.
* Per-feature bin metadata is carried as small arrays (num_bins, missing
  type, nan/zero/default bin) consumed by the jitted split finder.
* The histogram-construction dispatch (the reference's col-wise vs row-wise
  auto-benchmark, dataset.cpp:590-684) becomes the ``hist_method`` config
  switch: scatter-add (CPU oracle) vs one-hot matmul vs Pallas kernel.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from ..config import Config
from ..utils.log import log_fatal, log_info, log_warning
from .binning import (
    BIN_CATEGORICAL,
    BIN_NUMERICAL,
    MISSING_NAN,
    MISSING_NONE,
    MISSING_ZERO,
    BinMapper,
)


def _next_pow2(x: int) -> int:
    return 1 << max(0, (x - 1)).bit_length()


@dataclass
class Metadata:
    """Labels, weights, query boundaries, init scores
    (reference: class Metadata, include/LightGBM/dataset.h:40-248)."""

    label: Optional[np.ndarray] = None
    weight: Optional[np.ndarray] = None
    group: Optional[np.ndarray] = None          # per-query sizes
    query_boundaries: Optional[np.ndarray] = None  # cumulative, len num_queries+1
    init_score: Optional[np.ndarray] = None
    valid_rows: Optional[np.ndarray] = None     # bool mask: False marks the
                                                # phantom pad rows of process-
                                                # sharded datasets; None =
                                                # every row is real

    def set_group(self, group: Optional[np.ndarray]) -> None:
        if group is None:
            self.group = None
            self.query_boundaries = None
            return
        group = np.asarray(group, dtype=np.int64).ravel()
        self.group = group
        self.query_boundaries = np.concatenate([[0], np.cumsum(group)])

    def num_queries(self) -> int:
        return 0 if self.group is None else len(self.group)


class BinnedDataset:
    """Feature-binned training data + metadata.

    ``binned``: (num_features, num_data) np.uint8/np.int16 — bin indices.
    """

    def __init__(
        self,
        binned: Optional[np.ndarray],
        bin_mappers: List[BinMapper],
        metadata: Metadata,
        feature_names: Optional[List[str]] = None,
        max_bin: int = 255,
        num_data: Optional[int] = None,
    ):
        self.binned = binned          # (F, N) dense bins; None for the
                                      # sparse-input path (bundled only)
        self.bundled = None           # (BF, N) EFB matrix (io/bundle.py)
        self.bundle_layout = None
        self.bin_mappers = bin_mappers
        self.metadata = metadata
        self.num_features = len(bin_mappers)
        self.num_data = binned.shape[1] if binned is not None else num_data
        self.max_bin = max_bin
        self.feature_names = feature_names or [
            f"Column_{i}" for i in range(self.num_features)
        ]
        self._build_feature_meta()

    # ------------------------------------------------------------------
    @property
    def train_matrix(self) -> np.ndarray:
        """The matrix the trainer uploads: the EFB-bundled columns when
        bundling applied, else the plain (F, N) binned matrix."""
        return self.bundled if self.bundled is not None else self.binned

    def bundle_features(self, config: Config,
                        reference: Optional["BinnedDataset"] = None) -> None:
        """Apply Exclusive Feature Bundling (reference: enable_bundle,
        Dataset::Construct -> FindGroups/FastFeatureBundling,
        src/io/dataset.cpp:97-315).  Valid sets reuse the training layout."""
        from .bundle import apply_bundles_dense, maybe_bundle

        if self.binned is None:
            return  # sparse path bundles at construction time
        if reference is not None:
            if reference.bundle_layout is not None:
                self.bundle_layout = reference.bundle_layout
                self.bundled = apply_bundles_dense(
                    self.binned, self.zero_bins, self.bundle_layout)
            return
        bundled, layout = maybe_bundle(
            self.binned, self.zero_bins, self.num_bins,
            max_conflict_rate=config.max_conflict_rate)
        if layout is not None:
            self.bundled = bundled
            self.bundle_layout = layout

    @property
    def padded_bundle_bin(self) -> int:
        assert self.bundle_layout is not None
        return max(8, _next_pow2(int(self.bundle_layout.bundle_nbins.max())))

    # ------------------------------------------------------------------
    def _build_feature_meta(self) -> None:
        F = self.num_features
        self.num_bins = np.array([m.num_bin for m in self.bin_mappers], dtype=np.int32)
        self.missing_types = np.array(
            [m.missing_type for m in self.bin_mappers], dtype=np.int32
        )
        self.nan_bins = np.array([m.nan_bin for m in self.bin_mappers], dtype=np.int32)
        self.zero_bins = np.array([m.zero_bin for m in self.bin_mappers], dtype=np.int32)
        self.default_bins = np.array(
            [m.default_bin for m in self.bin_mappers], dtype=np.int32
        )
        self.is_categorical = np.array(
            [m.bin_type == BIN_CATEGORICAL for m in self.bin_mappers], dtype=bool
        )
        self.is_trivial = np.array([m.is_trivial for m in self.bin_mappers], dtype=bool)
        # padded bin-axis size for histogram arrays (TPU lane alignment)
        max_nb = int(self.num_bins.max()) if F else 2
        self.num_total_bin = max(2, max_nb)
        self.padded_bin = max(8, _next_pow2(self.num_total_bin))

    # ------------------------------------------------------------------
    @classmethod
    def from_numpy(
        cls,
        X: np.ndarray,
        label: Optional[np.ndarray] = None,
        weight: Optional[np.ndarray] = None,
        group: Optional[np.ndarray] = None,
        init_score: Optional[np.ndarray] = None,
        config: Optional[Config] = None,
        categorical_features: Optional[Sequence[int]] = None,
        feature_names: Optional[List[str]] = None,
        reference: Optional["BinnedDataset"] = None,
        bin_finder=None,
    ) -> "BinnedDataset":
        """Build a binned dataset from a dense float matrix (rows, features).

        ``reference``: reuse another dataset's bin mappers (validation sets
        must share the training bins — reference basic.py Dataset reference
        alignment semantics).
        ``bin_finder``: optional callable(list-of-sample-arrays, config) ->
        list[BinMapper] used by the distributed loader to sync mappers.
        """
        config = config or Config()
        X = np.asarray(X)
        if X.ndim != 2:
            raise ValueError("X must be 2-D (rows, features)")
        num_data, num_features = X.shape
        categorical = set(categorical_features or [])

        if reference is not None:
            mappers = reference.bin_mappers
            feature_names = feature_names or reference.feature_names
        else:
            # sampling (reference: bin_construct_sample_cnt, dataset_loader.cpp:823)
            sample_cnt = min(num_data, config.bin_construct_sample_cnt)
            rng = np.random.RandomState(config.data_random_seed)
            if sample_cnt < num_data:
                sample_idx = rng.choice(num_data, size=sample_cnt, replace=False)
            else:
                sample_idx = np.arange(num_data)
            max_bins = list(config.max_bin_by_feature) or [config.max_bin] * num_features
            if len(max_bins) != num_features:
                log_fatal("max_bin_by_feature length must equal number of features")
            samples = [np.asarray(X[sample_idx, j], dtype=np.float64) for j in range(num_features)]
            if bin_finder is not None:
                mappers = bin_finder(samples, sample_cnt, max_bins, categorical,
                                     config, num_data)
            else:
                from .binning import get_forced_bins

                forced = get_forced_bins(config.forcedbins_filename,
                                         num_features, categorical)
                mappers = [
                    BinMapper.find_bin(
                        samples[j],
                        total_sample_cnt=sample_cnt,
                        max_bin=max_bins[j],
                        min_data_in_bin=config.min_data_in_bin,
                        bin_type=BIN_CATEGORICAL if j in categorical else BIN_NUMERICAL,
                        use_missing=config.use_missing,
                        zero_as_missing=config.zero_as_missing,
                        forced_bounds=forced[j],
                        pre_filter=config.feature_pre_filter,
                        filter_cnt=int(config.min_data_in_leaf * sample_cnt
                                       / max(num_data, 1)),
                    )
                    for j in range(num_features)
                ]

        max_nb = max(m.num_bin for m in mappers) if mappers else 2
        dtype = np.uint8 if max_nb <= 256 else np.int16
        binned = np.empty((num_features, num_data), dtype=dtype)
        for j, m in enumerate(mappers):
            binned[j] = m.value_to_bin(X[:, j]).astype(dtype)

        meta = Metadata()
        if label is not None:
            meta.label = np.asarray(label, dtype=np.float32).ravel()
            if len(meta.label) != num_data:
                log_fatal("label length mismatch")
        if weight is not None:
            meta.weight = np.asarray(weight, dtype=np.float32).ravel()
        if init_score is not None:
            meta.init_score = np.asarray(init_score, dtype=np.float64)
        meta.set_group(group)
        ds = cls(binned, mappers, meta, feature_names, max_bin=config.max_bin)
        n_used = int((~ds.is_trivial).sum())
        log_info(
            f"Constructed binned dataset: {num_data} rows, {num_features} features "
            f"({n_used} informative), max {ds.num_total_bin} bins"
        )
        if config.enable_bundle:
            ds.bundle_features(config, reference=reference)
        return ds

    # ------------------------------------------------------------------
    @classmethod
    def from_csr(
        cls,
        indptr: np.ndarray,
        indices: np.ndarray,
        values: np.ndarray,
        num_data: int,
        num_features: int,
        label=None,
        weight=None,
        group=None,
        init_score=None,
        config: Optional[Config] = None,
        categorical_features: Optional[Sequence[int]] = None,
        feature_names: Optional[List[str]] = None,
        reference: Optional["BinnedDataset"] = None,
    ) -> "BinnedDataset":
        """Build from CSR triplets WITHOUT materializing the dense (F, N)
        matrix — the wide-sparse input path (reference:
        ``LGBM_DatasetCreateFromCSR`` src/c_api.cpp + sparse push into
        FeatureGroups).  Sampling uses the sparse contract of
        ``BinMapper.find_bin`` (absent entries are implicit zeros), and the
        training representation is built directly as EFB bundle columns
        (io/bundle.py), so peak memory is O(nnz + num_bundles * num_data).
        """
        from .bundle import BundleLayout, apply_bundles_csr, find_bundles

        config = config or Config()
        indptr = np.asarray(indptr, np.int64)
        indices = np.asarray(indices, np.int32)
        values = np.asarray(values, np.float64)
        categorical = set(categorical_features or [])
        rows = np.repeat(np.arange(num_data), np.diff(indptr))

        if reference is not None:
            mappers = reference.bin_mappers
            feature_names = feature_names or reference.feature_names
        else:
            sample_cnt = min(num_data, config.bin_construct_sample_cnt)
            rng = np.random.RandomState(config.data_random_seed)
            samp = (rng.choice(num_data, size=sample_cnt, replace=False)
                    if sample_cnt < num_data else np.arange(num_data))
            in_sample = np.zeros(num_data, bool)
            in_sample[samp] = True
            sel = in_sample[rows]
            f_sel, v_sel = indices[sel], values[sel]
            order = np.argsort(f_sel, kind="stable")
            f_sorted, v_sorted = f_sel[order], v_sel[order]
            starts = np.searchsorted(f_sorted, np.arange(num_features + 1))
            max_bins = (list(config.max_bin_by_feature)
                        or [config.max_bin] * num_features)
            if len(max_bins) != num_features:
                log_fatal("max_bin_by_feature length must equal number of "
                          "features")
            from .binning import get_forced_bins

            forced = get_forced_bins(config.forcedbins_filename,
                                     num_features, categorical)
            mappers = [
                BinMapper.find_bin(
                    v_sorted[starts[j]:starts[j + 1]],
                    total_sample_cnt=sample_cnt,
                    max_bin=max_bins[j],
                    min_data_in_bin=config.min_data_in_bin,
                    bin_type=(BIN_CATEGORICAL if j in categorical
                              else BIN_NUMERICAL),
                    use_missing=config.use_missing,
                    zero_as_missing=config.zero_as_missing,
                    forced_bounds=forced[j],
                    pre_filter=config.feature_pre_filter,
                    filter_cnt=int(config.min_data_in_leaf * sample_cnt
                                   / max(num_data, 1)),
                )
                for j in range(num_features)
            ]

        meta = Metadata()
        if label is not None:
            meta.label = np.asarray(label, dtype=np.float32).ravel()
            if len(meta.label) != num_data:
                log_fatal("label length mismatch")
        if weight is not None:
            meta.weight = np.asarray(weight, dtype=np.float32).ravel()
        if init_score is not None:
            meta.init_score = np.asarray(init_score, dtype=np.float64)
        meta.set_group(group)

        ds = cls(None, mappers, meta, feature_names,
                 max_bin=config.max_bin, num_data=num_data)

        # bin the non-zero entries feature-by-feature (host, vectorized via
        # one stable sort over the nnz instead of F passes)
        bin_values = np.zeros(len(values), np.int32)
        order_all = np.argsort(indices, kind="stable")
        starts_all = np.searchsorted(indices[order_all],
                                     np.arange(num_features + 1))
        for j in range(num_features):
            seg = order_all[starts_all[j]:starts_all[j + 1]]
            if len(seg):
                bin_values[seg] = mappers[j].value_to_bin(values[seg])

        if reference is not None and reference.bundle_layout is not None:
            layout = reference.bundle_layout
        elif reference is not None:
            # unbundled reference (e.g. dense training data that found no
            # exclusivity): identity bundles keep bundle bins == original
            # bins so the matrices stay directly comparable
            layout = BundleLayout(
                bundle_of=np.arange(num_features, dtype=np.int32),
                offset=np.zeros(num_features, np.int32),
                is_bundled=np.zeros(num_features, bool),
                bundle_nbins=np.asarray(ds.num_bins, np.int32),
            )
        else:
            # conflict masks from the sampled non-zero pattern
            sample_cnt_c = min(num_data, 32768)
            rng2 = np.random.RandomState(config.data_random_seed + 1)
            samp2 = (rng2.choice(num_data, size=sample_cnt_c, replace=False)
                     if sample_cnt_c < num_data else np.arange(num_data))
            pos = np.full(num_data, -1, np.int64)
            pos[samp2] = np.arange(len(samp2))
            masks = np.zeros((num_features, len(samp2)), bool)
            r_pos = pos[rows]
            hit = (r_pos >= 0) & (bin_values != ds.zero_bins[indices])
            masks[indices[hit], r_pos[hit]] = True
            layout = (find_bundles(masks, ds.num_bins,
                                   config.max_conflict_rate)
                      if config.enable_bundle else None)
            if layout is None:
                # no exclusivity to exploit: fall back to identity bundles
                layout = BundleLayout(
                    bundle_of=np.arange(num_features, dtype=np.int32),
                    offset=np.zeros(num_features, np.int32),
                    is_bundled=np.zeros(num_features, bool),
                    bundle_nbins=np.asarray(ds.num_bins, np.int32),
                )
        built = apply_bundles_csr(indptr, indices, bin_values,
                                  num_data, ds.zero_bins, layout)
        if not layout.is_bundled.any():
            # identity layout: bundle bins == original bins, so this IS the
            # plain dense binned matrix — record it as such (no decode path,
            # no spurious EFB incompatibility gates)
            ds.binned = built
        else:
            ds.bundle_layout = layout
            ds.bundled = built
        log_info(
            f"Constructed sparse binned dataset: {num_data} rows, "
            f"{num_features} features -> {layout.num_bundles} bundle "
            f"columns ({len(values)} non-zeros)")
        return ds

    # ------------------------------------------------------------------
    # Binary dataset cache (reference: Dataset::SaveBinaryFile dataset.h:473,
    # DatasetLoader::LoadFromBinFile dataset_loader.cpp:273) — skips
    # re-parsing and re-binning on subsequent runs.  Serialized with numpy's
    # npz container; the bin mappers ride as flat arrays via
    # BinMapper.to_arrays/from_arrays (also the wire format a distributed
    # bin-finding allgather would exchange, dataset_loader.cpp:913-996).
    # ------------------------------------------------------------------
    BINARY_MAGIC = "lightgbmv1_tpu.dataset.v1"
    # format_version 2 (PR 8): per-section SHA-256 digests + atomic write
    # — a torn or bit-rotted cache fails LOUDLY at load instead of
    # training on garbage.  Version-1 caches (no digests) still load,
    # with a warning.
    BINARY_FORMAT_VERSION = 2

    @staticmethod
    def _section_digest(arr: np.ndarray) -> str:
        import hashlib

        return hashlib.sha256(np.ascontiguousarray(arr).tobytes()
                              ).hexdigest()

    def save_binary(self, path: str) -> None:
        ubounds = [np.asarray(m.bin_upper_bound, np.float64)
                   for m in self.bin_mappers]
        cats = [np.asarray(m.bin_2_categorical, np.int64)
                for m in self.bin_mappers]
        scalars = np.array(
            [[m.num_bin, m.missing_type, m.bin_type, int(m.is_trivial)]
             for m in self.bin_mappers], dtype=np.int64)
        floats = np.array(
            [[m.sparse_rate, m.min_value, m.max_value]
             for m in self.bin_mappers], dtype=np.float64)
        meta = self.metadata
        import io as _io

        from ..utils.fileio import atomic_write_bytes

        fh = _io.BytesIO()          # keep the exact filename (savez appends
                                    # .npz to bare string paths)
        bl = self.bundle_layout
        sections = dict(
            magic=np.frombuffer(self.BINARY_MAGIC.encode(), dtype=np.uint8),
            # sparse-path datasets carry only the EFB bundle matrix;
            # load_binary reconstructs whichever representation was saved
            binned=(self.binned if self.binned is not None
                    else np.zeros((0, 0), np.uint8)),
            # dense-path bundles are re-derived on load from binned + the
            # layout (writing both matrices would double the cache size);
            # only the sparse path persists the bundle matrix itself
            bundled=(self.bundled
                     if self.bundled is not None and self.binned is None
                     else np.zeros((0, 0), np.uint8)),
            bundle_of=(bl.bundle_of if bl is not None
                       else np.zeros(0, np.int32)),
            bundle_offset=(bl.offset if bl is not None
                           else np.zeros(0, np.int32)),
            bundle_is_bundled=(bl.is_bundled if bl is not None
                               else np.zeros(0, bool)),
            bundle_nbins=(bl.bundle_nbins if bl is not None
                          else np.zeros(0, np.int32)),
            num_data=np.int64(self.num_data),
            max_bin=np.int64(self.max_bin),
            feature_names=np.array(self.feature_names),
            mapper_scalars=scalars,
            mapper_floats=floats,
            ubound_flat=np.concatenate(ubounds) if ubounds else np.zeros(0),
            ubound_offsets=np.cumsum([0] + [len(u) for u in ubounds]),
            cat_flat=np.concatenate(cats) if cats else np.zeros(0, np.int64),
            cat_offsets=np.cumsum([0] + [len(c) for c in cats]),
            label=meta.label if meta.label is not None else np.zeros(0),
            weight=meta.weight if meta.weight is not None else np.zeros(0),
            group=meta.group if meta.group is not None else np.zeros(0, np.int64),
            init_score=(meta.init_score if meta.init_score is not None
                        else np.zeros(0)),
        )
        digest_keys = sorted(k for k in sections if k != "magic")
        digests = np.array([self._section_digest(sections[k])
                            for k in digest_keys])
        np.savez_compressed(
            fh,
            format_version=np.int64(self.BINARY_FORMAT_VERSION),
            digest_keys=np.array(digest_keys),
            digest_values=digests,
            **sections,
        )
        # atomic (tmp+fsync+rename): a kill mid-save leaves the previous
        # cache intact; the ``file_write`` fault-injection seam rides along
        # (tests/test_stream_cache.py corrupts/tears through it)
        atomic_write_bytes(path, fh.getvalue(), site=path)
        log_info(f"Saved binary dataset cache to {path} "
                 f"(format v{self.BINARY_FORMAT_VERSION}, "
                 f"{len(digest_keys)} digest-pinned sections)")

    @classmethod
    def is_binary_file(cls, path: str) -> bool:
        import zipfile

        from ..utils.fileio import exists, open_file

        if not exists(path):
            return False
        try:
            with open_file(path, "rb") as fh:
                if not zipfile.is_zipfile(fh):
                    return False
                fh.seek(0)
                with np.load(fh, allow_pickle=False) as z:
                    return ("magic" in z and
                            bytes(z["magic"]).decode() == cls.BINARY_MAGIC)
        except Exception:
            return False

    @classmethod
    def load_binary(cls, path: str) -> "BinnedDataset":
        import zipfile

        from ..utils.fileio import open_file
        from ..utils.log import LightGBMError

        try:
            return cls._load_binary_inner(path, open_file)
        except LightGBMError:
            raise
        except (zipfile.BadZipFile, ValueError, OSError, KeyError,
                EOFError) as e:
            # a torn/truncated/corrupt cache must fail LOUDLY here — the
            # pre-v2 reader could hand back garbage arrays from a half
            # written zip
            log_fatal(f"{path}: torn or corrupt binary dataset cache "
                      f"({type(e).__name__}: {e}); re-create it with "
                      "save_binary")

    @classmethod
    def _load_binary_inner(cls, path: str, open_file) -> "BinnedDataset":
        with open_file(path, "rb") as fh, \
                np.load(fh, allow_pickle=False) as z:
            if bytes(z["magic"]).decode() != cls.BINARY_MAGIC:
                log_fatal(f"{path} is not a lightgbmv1_tpu binary dataset")
            version = (int(z["format_version"])
                       if "format_version" in z else 1)
            if version > cls.BINARY_FORMAT_VERSION:
                log_fatal(
                    f"{path}: binary cache format v{version} is newer "
                    f"than this build reads "
                    f"(v{cls.BINARY_FORMAT_VERSION}); re-create it with "
                    "save_binary")
            if version >= 2:
                keys = [str(s) for s in z["digest_keys"]]
                vals = [str(s) for s in z["digest_values"]]
                for k, want in zip(keys, vals):
                    if k not in z or cls._section_digest(z[k]) != want:
                        log_fatal(
                            f"{path}: binary cache section {k!r} digest "
                            "mismatch — torn or corrupt cache; re-create "
                            "it with save_binary")
            else:
                log_warning(f"{path}: legacy v1 binary cache (no section "
                            "digests); re-save to enable corruption "
                            "detection")
            scalars = z["mapper_scalars"]
            floats = z["mapper_floats"]
            uoff = z["ubound_offsets"]
            coff = z["cat_offsets"]
            mappers = []
            for j in range(scalars.shape[0]):
                mappers.append(BinMapper.from_arrays({
                    "bin_upper_bound": z["ubound_flat"][uoff[j]:uoff[j + 1]],
                    "num_bin": scalars[j, 0],
                    "missing_type": scalars[j, 1],
                    "bin_type": scalars[j, 2],
                    "is_trivial": scalars[j, 3],
                    "sparse_rate": floats[j, 0],
                    "min_value": floats[j, 1],
                    "max_value": floats[j, 2],
                    "bin_2_categorical": z["cat_flat"][coff[j]:coff[j + 1]],
                }))
            meta = Metadata()
            if z["label"].size:
                meta.label = z["label"].astype(np.float32)
            if z["weight"].size:
                meta.weight = z["weight"].astype(np.float32)
            if z["group"].size:
                meta.set_group(z["group"])
            if z["init_score"].size:
                meta.init_score = z["init_score"]
            binned = z["binned"] if z["binned"].size else None
            num_data = (int(z["num_data"]) if "num_data" in z
                        else z["binned"].shape[1])
            ds = cls(binned, mappers, meta,
                     feature_names=[str(s) for s in z["feature_names"]],
                     max_bin=int(z["max_bin"]), num_data=num_data)
            if "bundle_of" in z and z["bundle_of"].size:
                from .bundle import BundleLayout, apply_bundles_dense

                ds.bundle_layout = BundleLayout(
                    bundle_of=z["bundle_of"], offset=z["bundle_offset"],
                    is_bundled=z["bundle_is_bundled"],
                    bundle_nbins=z["bundle_nbins"])
                ds.bundled = (z["bundled"] if z["bundled"].size
                              else apply_bundles_dense(
                                  ds.binned, ds.zero_bins,
                                  ds.bundle_layout))
        log_info(f"Loaded binary dataset cache from {path}: "
                 f"{ds.num_data} rows, {ds.num_features} features")
        return ds

    # ------------------------------------------------------------------
    def bin_raw_features(self, X: np.ndarray) -> np.ndarray:
        """Bin new raw data with this dataset's mappers → (F, N) bins."""
        X = np.asarray(X)
        dtype = (self.binned.dtype if self.binned is not None
                 else (np.uint8 if self.num_total_bin <= 256 else np.int16))
        out = np.empty((self.num_features, X.shape[0]), dtype=dtype)
        for j, m in enumerate(self.bin_mappers):
            out[j] = m.value_to_bin(X[:, j]).astype(dtype)
        return out

    def feature_infos(self) -> List[str]:
        return [m.feature_info_str() for m in self.bin_mappers]

    @property
    def num_used_features(self) -> int:
        return int((~self.is_trivial).sum())
