"""Plotting utilities — importance / metric / split-histogram / tree.

API-compatible re-implementation of the reference plotting module
(reference: python-package/lightgbm/plotting.py — plot_importance :21,
plot_split_value_histogram :118, plot_metric :208, plot_tree :537,
create_tree_digraph :420).  matplotlib and graphviz are imported lazily and
raise the reference's ImportError messages when absent.
"""

from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .basic import Booster
from .utils.log import log_warning


def _check_not_tuple_of_2_elements(obj, obj_name: str) -> None:
    if not isinstance(obj, (list, tuple)) or len(obj) != 2:
        raise TypeError(f"{obj_name} must be a list/tuple of 2 elements")


def _get_matplotlib():
    try:
        import matplotlib.pyplot as plt
        return plt
    except ImportError:
        raise ImportError("You must install matplotlib "
                          "to plot importance/metric/split histograms.")


def plot_importance(
    booster,
    ax=None,
    height: float = 0.2,
    xlim=None,
    ylim=None,
    title: str = "Feature importance",
    xlabel: str = "Feature importance",
    ylabel: str = "Features",
    importance_type: str = "split",
    max_num_features: Optional[int] = None,
    ignore_zero: bool = True,
    figsize=None,
    dpi=None,
    grid: bool = True,
    precision: Optional[int] = 3,
    **kwargs,
):
    """Plot model feature importances (reference plotting.py:21)."""
    plt = _get_matplotlib()
    if isinstance(booster, Booster):
        importance = booster.feature_importance(importance_type=importance_type)
        feature_names = booster.feature_name()
    elif hasattr(booster, "booster_"):       # sklearn wrapper
        importance = booster.booster_.feature_importance(
            importance_type=importance_type)
        feature_names = booster.booster_.feature_name()
    else:
        raise TypeError("booster must be Booster or LGBMModel")

    tuples = sorted(zip(feature_names, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [x for x in tuples if x[1] > 0]
    if not tuples:
        raise ValueError("Cannot plot empty feature importances")
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    labels, values = zip(*tuples)

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        fmt = f"%.{precision}f" if (precision is not None
                                    and importance_type == "gain") else "%d"
        ax.text(x + 1, y, fmt % x, va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
    else:
        xlim = (0, max(values) * 1.1)
    ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        ylim = (-1, len(values))
    ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_split_value_histogram(
    booster,
    feature,
    bins=None,
    ax=None,
    width_coef: float = 0.8,
    xlim=None,
    ylim=None,
    title: Optional[str] = "Split value histogram for feature with @index/name@ @feature@",
    xlabel: Optional[str] = "Feature split value",
    ylabel: Optional[str] = "Count",
    figsize=None,
    dpi=None,
    grid: bool = True,
    **kwargs,
):
    """Histogram of split threshold values used for one feature
    (reference plotting.py:118)."""
    plt = _get_matplotlib()
    if hasattr(booster, "booster_"):
        booster = booster.booster_
    names = booster.feature_name()
    if isinstance(feature, str):
        fidx = names.index(feature)
    else:
        fidx = int(feature)
    values = []
    for t in booster._all_trees():
        for i in range(t.num_leaves - 1):
            if t.split_feature[i] == fidx and not t.is_cat[i]:
                values.append(float(t.threshold[i]))
    if not values:
        raise ValueError(
            "Cannot plot split value histogram, "
            f"because feature {feature} was not used in splitting")
    values = np.asarray(values)
    hist, bin_edges = np.histogram(values, bins=bins or "auto")
    centres = (bin_edges[:-1] + bin_edges[1:]) / 2
    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    width = width_coef * (bin_edges[1] - bin_edges[0])
    ax.bar(centres, hist, align="center", width=width, **kwargs)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
        ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        ylim = (0, max(hist) * 1.1)
    ax.set_ylim(ylim)
    if title is not None:
        title = title.replace("@feature@", str(feature)).replace(
            "@index/name@", "name" if isinstance(feature, str) else "index")
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(
    booster,
    metric: Optional[str] = None,
    dataset_names: Optional[List[str]] = None,
    ax=None,
    xlim=None,
    ylim=None,
    title: Optional[str] = "Metric during training",
    xlabel: Optional[str] = "Iterations",
    ylabel: Optional[str] = "@metric@",
    figsize=None,
    dpi=None,
    grid: bool = True,
):
    """Plot a metric recorded with record_evaluation (reference
    plotting.py:208). ``booster`` is the evals_result dict or an LGBMModel."""
    plt = _get_matplotlib()
    if isinstance(booster, dict):
        eval_results = deepcopy(booster)
    elif hasattr(booster, "evals_result_"):
        eval_results = deepcopy(booster.evals_result_)
    else:
        raise TypeError("booster must be dict or LGBMModel")
    if not eval_results:
        raise ValueError("eval results cannot be empty")

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)

    if dataset_names is None:
        dataset_names = list(eval_results.keys())
    name0 = dataset_names[0]
    metrics_for_one = eval_results[name0]
    if metric is None:
        if len(metrics_for_one) > 1:
            log_warning("More than one metric available, picking one to plot.")
        metric, results = list(metrics_for_one.items())[-1]
    else:
        results = metrics_for_one[metric]
    num_iteration = len(results)
    max_result = max(results)
    min_result = min(results)
    x_ = np.arange(num_iteration)
    for name in dataset_names:
        results = eval_results[name][metric]
        ax.plot(x_, results, label=name)
        max_result = max(max(results), max_result)
        min_result = min(min(results), min_result)
    ax.legend(loc="best")
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
    else:
        xlim = (0, num_iteration)
    ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        margin = 0.05 * (max_result - min_result + 1e-12)
        ylim = (min_result - margin, max_result + margin)
    ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel.replace("@metric@", metric))
    ax.grid(grid)
    return ax


# ---------------------------------------------------------------------------
# Tree visualization (graphviz)
# ---------------------------------------------------------------------------


def _tree_to_graph(tree, feature_names, precision=3, orientation="horizontal",
                   show_info=None, **kwargs):
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("You must install graphviz to plot tree.")
    show_info = show_info or []

    graph = Digraph(**kwargs)
    rankdir = "LR" if orientation == "horizontal" else "TB"
    graph.attr(rankdir=rankdir)

    def fmt(v):
        return f"{v:.{precision}f}"

    def add(node, parent=None, decision=None):
        if node >= 0:
            name = f"split{node}"
            f = int(tree.split_feature[node])
            fname = (feature_names[f] if feature_names is not None
                     else f"Column_{f}")
            if tree.is_cat[node]:
                cats = tree.cat_sets[node]
                if cats is None:
                    cats = tree.cat_bins_of(node)
                label = f"{fname} in " + "||".join(
                    str(int(c)) for c in np.asarray(cats)[:10])
            else:
                label = f"{fname} <= {fmt(float(tree.threshold[node]))}"
            if "split_gain" in show_info:
                label += f"\\ngain: {fmt(float(tree.split_gain[node]))}"
            if "internal_value" in show_info:
                label += f"\\nvalue: {fmt(float(tree.internal_value[node]))}"
            if "internal_count" in show_info:
                label += f"\\ncount: {int(tree.internal_count[node])}"
            graph.node(name, label=label, shape="rectangle")
            add(int(tree.left_child[node]), name, "yes")
            add(int(tree.right_child[node]), name, "no")
        else:
            leaf = -node - 1
            name = f"leaf{leaf}"
            label = f"leaf {leaf}: {fmt(float(tree.leaf_value[leaf]))}"
            if "leaf_count" in show_info:
                label += f"\\ncount: {int(tree.leaf_count[leaf])}"
            if "leaf_weight" in show_info:
                label += f"\\nweight: {fmt(float(tree.leaf_weight[leaf]))}"
            graph.node(name, label=label)
        if parent is not None:
            graph.edge(parent, name, decision)

    add(0 if tree.num_leaves > 1 else -1)
    return graph


def create_tree_digraph(
    booster,
    tree_index: int = 0,
    show_info: Optional[List[str]] = None,
    precision: Optional[int] = 3,
    orientation: str = "horizontal",
    **kwargs,
):
    """Create a graphviz Digraph of one tree (reference plotting.py:420)."""
    if hasattr(booster, "booster_"):
        booster = booster.booster_
    trees = booster._all_trees()
    if tree_index >= len(trees):
        raise IndexError("tree_index is out of range.")
    return _tree_to_graph(trees[tree_index], booster.feature_name(),
                          precision=precision, orientation=orientation,
                          show_info=show_info, **kwargs)


def plot_tree(
    booster,
    ax=None,
    tree_index: int = 0,
    figsize=None,
    dpi=None,
    show_info: Optional[List[str]] = None,
    precision: Optional[int] = 3,
    orientation: str = "horizontal",
    **kwargs,
):
    """Render one tree with matplotlib via graphviz (reference
    plotting.py:537)."""
    plt = _get_matplotlib()
    import io

    graph = create_tree_digraph(booster, tree_index=tree_index,
                                show_info=show_info, precision=precision,
                                orientation=orientation, **kwargs)
    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    from matplotlib.image import imread

    s = graph.pipe(format="png")
    ax.imshow(imread(io.BytesIO(s)))
    ax.axis("off")
    return ax
