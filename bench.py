"""Benchmark: HIGGS-shaped binary training throughput + AUC on one chip.

Reference baseline (BASELINE.md / docs/Experiments.rst:110-124): LightGBM
trains HIGGS (10.5M rows x 28 features, num_leaves=255) at 500 trees /
130.094 s on 2x Xeon E5-2690 v4 = **40.36M row-trees/s**.  The GPU-learner
benchmark config (docs/GPU-Performance.rst:108-124) uses max_bin=63; we
follow the GPU config for bins since that is the device-offload comparison
point.

This bench trains on a synthetic HIGGS-shaped dataset (same feature count,
bins, leaves) sized to this chip and reports:

    value       = trained rows*trees per second (millions), measured with a
                  full device sync (jax.device_get) — NOT block_until_ready,
                  which does not synchronize through the axon tunnel
    vs_baseline = value / 40.36   (>1 means faster than the reference CPU)
    auc         = held-out AUC after `auc_iters` total trees
    auc_ref     = reference LightGBM (C++, leaf-wise) AUC on the SAME data
                  and config, recorded from a run of the reference binary

See PERF.md for measured ceilings of the benchmarked device — the tunneled
single TPU chip in this environment sustains ~1.9 TF/s matmul and ~8.6 GB/s
HBM (about 1% of a physical v5e), which bounds any implementation far below
the 2x-Xeon baseline; vs_baseline on this device is therefore a relative
engineering metric, not a statement about TPU silicon.

Prints exactly one JSON line.
"""

import json
import os
import sys
import time

import numpy as np


def make_data(n, seed):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 28).astype(np.float32)
    logit = (X[:, 0] * 1.2 - X[:, 1] + 0.6 * X[:, 2] * X[:, 3]
             + 0.4 * X[:, 4] + 0.3 * np.sin(3.0 * X[:, 5]))
    y = (logit + rng.randn(n).astype(np.float32) > 0).astype(np.float64)
    return X, y


def main():
    import jax

    from lightgbmv1_tpu.config import Config
    from lightgbmv1_tpu.io.dataset import BinnedDataset
    from lightgbmv1_tpu.models.gbdt import create_boosting

    backend = jax.default_backend()
    N = int(os.environ.get("BENCH_ROWS", 1_000_000))
    TREES = int(os.environ.get("BENCH_TREES", 10))
    AUC_ITERS = int(os.environ.get("BENCH_AUC_ITERS", 100))
    N_TEST = 100_000
    if backend == "cpu":   # keep the CPU fallback quick
        N, TREES, AUC_ITERS, N_TEST = 50_000, 3, 20, 20_000

    X, y = make_data(N, 0)
    Xt, yt = make_data(N_TEST, 1)

    cfg = Config.from_dict({
        "objective": "binary",
        "num_leaves": 255,
        "max_bin": 63,            # GPU benchmark config (GPU-Performance.rst)
        "learning_rate": 0.1,
        "min_data_in_leaf": 20,
        "metric": "auc",
        "verbosity": -1,
        # batched frontier growth keeps the MXU busy (depthwise policy —
        # the same policy as xgboost_hist in the reference's comparison)
        "tree_growth": "levelwise",
    })
    ds = BinnedDataset.from_numpy(X, label=y, config=cfg)
    dt_test = BinnedDataset.from_numpy(Xt, label=yt, config=cfg, reference=ds)
    gbdt = create_boosting(cfg, ds)
    gbdt.add_valid(dt_test, "test")

    def sync():
        jax.device_get(gbdt._train_scores.score)

    # warmup: compiles the scanned multi-iteration step (same scan length
    # as the timed block — a different length would recompile).  The tunnel
    # adds run-to-run noise of up to ~30%, so every throughput number is the
    # best of 3 timed blocks (the block itself is a single device dispatch).
    gbdt.train_iters(TREES)
    sync()

    dt = 1e30
    for _ in range(3):
        t0 = time.time()
        gbdt.train_iters(TREES)
        sync()
        dt = min(dt, time.time() - t0)
    row_trees_per_s = N * TREES / dt / 1e6

    # the reference's own policy: leaf-wise (best-first), wave-batched
    # schedule (models/grower_wave.py)
    cfg_lw = Config.from_dict({**{k: getattr(cfg, k) for k in (
        "objective", "num_leaves", "max_bin", "learning_rate",
        "min_data_in_leaf", "metric")}, "verbosity": -1,
        "tree_growth": "leafwise"})
    gb_lw = create_boosting(cfg_lw, ds)
    gb_lw.add_valid(dt_test, "test")
    lw_trees = TREES
    gb_lw.train_iters(lw_trees)
    jax.device_get(gb_lw._train_scores.score)
    lw_dt = 1e30
    for _ in range(3):
        t0 = time.time()
        gb_lw.train_iters(lw_trees)
        jax.device_get(gb_lw._train_scores.score)
        lw_dt = min(lw_dt, time.time() - t0)
    leafwise_mrt = N * lw_trees / lw_dt / 1e6
    remaining_lw = max(AUC_ITERS - gb_lw.iter, 0)
    if remaining_lw:
        gb_lw.train_iters(remaining_lw)
        jax.device_get(gb_lw._train_scores.score)
    leafwise_auc = None
    for (_, name, value, _) in gb_lw.eval_valid():
        if name == "auc":
            leafwise_auc = float(value)

    # quality: continue to AUC_ITERS total trees, eval held-out AUC
    remaining = max(AUC_ITERS - gbdt.iter, 0)
    if remaining:
        gbdt.train_iters(remaining)
        sync()
    auc = None
    for (_, name, value, _) in gbdt.eval_valid():
        if name == "auc":
            auc = float(value)
    # reference LightGBM (C++ CLI built from /root/reference, run on THIS
    # host, leaf-wise, same synthetic data/config, 100 iters): valid AUC and
    # throughput measured 2026-07-30, recorded in PERF.md
    auc_ref = 0.913227          # reference valid_1 auc at iteration 100
    ref_same_host_mrt = 2.360   # reference M row-trees/s on this host's CPU

    baseline = 10.5e6 * 500 / 130.094 / 1e6   # reference CPU HIGGS throughput
    print(json.dumps({
        "metric": f"higgs-shaped binary training throughput ({backend}, "
                  f"{N} rows, 28 feat, 63 bins, 255 leaves)",
        "value": round(row_trees_per_s, 3),
        "unit": "M row-trees/s",
        "vs_baseline": round(row_trees_per_s / baseline, 4),
        "auc": round(auc, 5) if auc is not None else None,
        "auc_ref_lightgbm_cpp": auc_ref,
        "auc_iters": int(gbdt.iter),
        "train_seconds_for_timed_block": round(dt, 3),
        # the reference C++ CLI measured on THIS host's CPU (the 40.36 M
        # row-trees/s baseline machine is a 28-core dual-Xeon; see PERF.md)
        "ref_cpp_same_host_M_row_trees_per_s": ref_same_host_mrt,
        "vs_ref_same_host": round(row_trees_per_s / ref_same_host_mrt, 4),
        "leafwise_M_row_trees_per_s": round(leafwise_mrt, 3),
        "leafwise_auc": (round(leafwise_auc, 5)
                         if leafwise_auc is not None else None),
        # auc_iters fields record the ACTUAL tree counts behind each auc —
        # with BENCH_TREES overridden high the timed blocks can overshoot
        # AUC_ITERS, making the ref comparison no longer like-for-like
        "leafwise_auc_iters": int(gb_lw.iter),
        "leafwise_vs_ref_same_host": round(leafwise_mrt / ref_same_host_mrt,
                                           4),
    }))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:   # noqa: BLE001 — the driver records stdout; a
        # crash must still leave a parseable record of what happened
        import traceback

        traceback.print_exc()
        print(json.dumps({
            "metric": "higgs-shaped binary training throughput (FAILED)",
            "value": 0.0,
            "unit": "M row-trees/s",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}"[:300],
        }))
        sys.exit(1)   # truthful exit code alongside the parseable record
