"""Benchmark: HIGGS-shaped binary training throughput on one chip.

Reference baseline (BASELINE.md / docs/Experiments.rst:110-124): LightGBM
trains HIGGS (10.5M rows x 28 features, num_leaves=255, max_bin=255) at
500 trees / 130.094 s on 2x Xeon E5-2690 v4 = **40.36M row-trees/s**.
The GPU-learner benchmark config (docs/GPU-Performance.rst:108-124) uses
max_bin=63; we follow the GPU config for bins since that is the
device-offload comparison point.

This bench trains on a synthetic HIGGS-shaped dataset (same feature count,
bins, leaves) sized to this chip and reports throughput in the same unit:

    value       = trained rows*trees per second (millions)
    vs_baseline = value / 40.36   (>1 means faster than the reference CPU)

Prints exactly one JSON line.
"""

import json
import os
import sys
import time

import numpy as np


def main():
    import jax

    from lightgbmv1_tpu.config import Config
    from lightgbmv1_tpu.io.dataset import BinnedDataset
    from lightgbmv1_tpu.models.gbdt import create_boosting

    backend = jax.default_backend()
    # HIGGS shape: 28 features; rows scaled down for bench wall-clock
    N = int(os.environ.get("BENCH_ROWS", 1_000_000))
    F = 28
    TREES = int(os.environ.get("BENCH_TREES", 20))
    if backend == "cpu":   # keep the CPU fallback quick
        N, TREES = 100_000, 5

    rng = np.random.RandomState(0)
    X = rng.randn(N, F).astype(np.float32)
    logit = X[:, 0] * 1.2 - X[:, 1] + 0.6 * X[:, 2] * X[:, 3] + 0.4 * X[:, 4]
    y = (logit + rng.randn(N).astype(np.float32) > 0).astype(np.float64)

    cfg = Config.from_dict({
        "objective": "binary",
        "num_leaves": 255,
        "max_bin": 63,            # GPU benchmark config (GPU-Performance.rst)
        "learning_rate": 0.1,
        "min_data_in_leaf": 20,
        "verbosity": -1,
        # batched frontier growth keeps the MXU busy (depthwise policy —
        # the same policy as xgboost_hist in the reference's comparison)
        "tree_growth": "levelwise",
    })
    ds = BinnedDataset.from_numpy(X, label=y, config=cfg)
    gbdt = create_boosting(cfg, ds)

    # warmup: compiles the scanned multi-iteration step
    gbdt.train_iters(TREES)
    jax.block_until_ready(gbdt._train_scores.score)

    t0 = time.time()
    gbdt.train_iters(TREES)
    jax.block_until_ready(gbdt._train_scores.score)
    dt = time.time() - t0

    row_trees_per_s = N * TREES / dt / 1e6
    baseline = 10.5e6 * 500 / 130.094 / 1e6   # reference CPU HIGGS throughput
    print(json.dumps({
        "metric": f"higgs-shaped binary training throughput ({backend}, "
                  f"{N} rows, 28 feat, 63 bins, 255 leaves)",
        "value": round(row_trees_per_s, 3),
        "unit": "M row-trees/s",
        "vs_baseline": round(row_trees_per_s / baseline, 4),
    }))


if __name__ == "__main__":
    main()
