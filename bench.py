"""Benchmark: HIGGS-shaped binary training throughput + AUC on one chip.

Reference baseline (BASELINE.md / docs/Experiments.rst:110-124): LightGBM
trains HIGGS (10.5M rows x 28 features, num_leaves=255) at 500 trees /
130.094 s on 2x Xeon E5-2690 v4 = **40.36M row-trees/s**.  The GPU-learner
benchmark config (docs/GPU-Performance.rst:108-124) uses max_bin=63; we
follow the GPU config for bins since that is the device-offload comparison
point.

This bench trains on a synthetic HIGGS-shaped dataset (same feature count,
bins, leaves) sized to this chip and reports:

    value       = trained rows*trees per second (millions), measured with a
                  full device sync (jax.device_get) — NOT block_until_ready,
                  which does not synchronize through the axon tunnel
    vs_baseline = value / 40.36   (>1 means faster than the reference CPU)
    auc         = held-out AUC after `auc_iters` total trees
    auc_ref     = reference LightGBM (C++, leaf-wise) AUC on the SAME data
                  and config, recorded from a run of the reference binary

See PERF.md for measured ceilings of the benchmarked device.  The chip is
reached through a network tunnel with ~113 ms round-trip dispatch latency,
so everything is measured with multi-iteration scanned steps (one dispatch
per timed block); compute-wise the tunneled chip profiles near physical
v5e rates once dispatch is amortized (tools/microbench_hist.py measures
the device matmul peak used for the roofline fraction below).
vs_baseline compares against the 2x-Xeon HIGGS number from
docs/Experiments.rst; vs_ref_same_host against the reference C++ binary
run on THIS host — the like-for-like comparison.

Prints exactly one JSON line.
"""

import json
import os
import sys
import time

import numpy as np


def make_data(n, seed):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 28).astype(np.float32)
    logit = (X[:, 0] * 1.2 - X[:, 1] + 0.6 * X[:, 2] * X[:, 3]
             + 0.4 * X[:, 4] + 0.3 * np.sin(3.0 * X[:, 5]))
    y = (logit + rng.randn(n).astype(np.float32) > 0).astype(np.float64)
    return X, y


def measure_hist_and_roofline(ds, N):
    """Measured feature-histogram pass time + roofline fraction — the
    BASELINE.json tracked metric ("feature-histogram build ms/iter") and
    the evidence behind PERF.md's kernel-quality claim.  Methodology of
    docs/GPU-Performance.rst:108-124 (time the device histogram kernel on
    the benchmark config), plus a same-session matmul peak measurement so
    the roofline fraction compares against THIS device's real ceiling.
    Every number is from R reps inside one jit scan (one dispatch), with
    per-rep input perturbation to defeat CSE."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from lightgbmv1_tpu.ops.histogram import hist_wave

    SLOTS = 64            # the wave grower's K+1 slots at auto K=64
    B = 64                # padded bin axis for max_bin=63
    binned = jnp.asarray(ds.train_matrix)
    F = binned.shape[0]
    rng = np.random.RandomState(7)
    g3 = jnp.asarray(rng.randn(N, 3).astype(np.float32))
    label = jnp.asarray(rng.randint(0, SLOTS, size=N).astype(np.int32))

    from lightgbmv1_tpu.ops.histogram import default_hist_method

    method = default_hist_method("auto", binned.dtype)

    def timed_per_rep(make_reps, r1, r2):
        """Per-rep seconds from a TWO-length-scan differential: wall(r2) -
        wall(r1) over (r2 - r1) reps cancels dispatch latency and other
        per-call fixed costs (the ~113 ms tunnel round-trip would otherwise
        dominate and overstate per-rep time severalfold)."""
        f1, f2 = make_reps(r1), make_reps(r2)
        jax.device_get(f1())
        jax.device_get(f2())
        diffs = []
        for _ in range(5):
            t0 = time.time()
            jax.device_get(f1())
            t1 = time.time()
            jax.device_get(f2())
            t2 = time.time()
            diffs.append(((t2 - t1) - (t1 - t0)) / (r2 - r1))
        # MEDIAN, not min: the minimum of a difference of two noisy walls
        # can go spuriously small (slow short run + fast long run) and
        # overstate throughput past physical peaks
        return max(float(np.median(diffs)), 1e-9)

    def hist_make(r):
        @jax.jit
        def reps():
            def body(c, i):
                g = g3 * (1.0 + 1e-6 * i.astype(jnp.float32))  # defeat CSE
                h = hist_wave(binned, g, label, SLOTS, B, method=method)
                return c + h.sum(), None
            s, _ = lax.scan(body, jnp.float32(0), jnp.arange(r))
            return s
        return reps

    per_pass = timed_per_rep(hist_make, 4, 16)
    hist_ms = per_pass * 1e3
    # one-hot MXU formulation: (3*(SLOTS+1), rows) @ (rows, B*F) per pass,
    # bf16x2 = 2 passes (ops/hist_pallas.py)
    hist_flops = 2 * 3 * (SLOTS + 1) * N * B * F * 2
    hist_tfs = hist_flops / per_pass / 1e12

    # device matmul peak, same session, same measurement discipline
    M = 4096
    a = jnp.asarray(rng.randn(M, M).astype(np.float32), jnp.bfloat16)
    b = jnp.asarray(rng.randn(M, M).astype(np.float32), jnp.bfloat16)

    def mm_make(r):
        @jax.jit
        def reps():
            def body(c, i):
                out = jnp.dot(a * (1 + 1e-3 * i.astype(jnp.bfloat16)), b,
                              preferred_element_type=jnp.float32)
                return c + out.sum(), None
            s, _ = lax.scan(body, jnp.float32(0), jnp.arange(r))
            return s
        return reps

    peak_tfs = (2 * M ** 3) / timed_per_rep(mm_make, 8, 64) / 1e12
    return {
        "hist_ms_per_pass": round(hist_ms, 2),
        # a 255-leaf wave tree runs ceil(254/64) = 4 wave rounds per iter
        # (auto wave K = num_leaves/4, smaller-child subtraction pass)
        "hist_ms_per_iter": round(hist_ms * 4, 2),
        "hist_achieved_tf_s": round(hist_tfs, 2),
        "device_matmul_peak_tf_s": round(peak_tfs, 2),
        "hist_roofline_frac": round(hist_tfs / peak_tfs, 4),
    }


def main():
    import jax

    from lightgbmv1_tpu.config import Config
    from lightgbmv1_tpu.io.dataset import BinnedDataset
    from lightgbmv1_tpu.models.gbdt import create_boosting

    backend = jax.default_backend()
    N = int(os.environ.get("BENCH_ROWS", 1_000_000))
    TREES = int(os.environ.get("BENCH_TREES", 10))
    AUC_ITERS = int(os.environ.get("BENCH_AUC_ITERS", 100))
    N_TEST = 100_000
    if backend == "cpu":   # keep the CPU fallback quick
        N, TREES, AUC_ITERS, N_TEST = 50_000, 3, 20, 20_000

    X, y = make_data(N, 0)
    Xt, yt = make_data(N_TEST, 1)

    cfg = Config.from_dict({
        "objective": "binary",
        "num_leaves": 255,
        "max_bin": 63,            # GPU benchmark config (GPU-Performance.rst)
        "learning_rate": 0.1,
        "min_data_in_leaf": 20,
        "metric": "auc",
        "verbosity": -1,
        # batched frontier growth keeps the MXU busy (depthwise policy —
        # the same policy as xgboost_hist in the reference's comparison)
        "tree_growth": "levelwise",
    })
    ds = BinnedDataset.from_numpy(X, label=y, config=cfg)
    dt_test = BinnedDataset.from_numpy(Xt, label=yt, config=cfg, reference=ds)
    gbdt = create_boosting(cfg, ds)
    gbdt.add_valid(dt_test, "test")

    def sync():
        jax.device_get(gbdt._train_scores.score)

    # warmup: compiles the scanned multi-iteration step (same scan length
    # as the timed block — a different length would recompile).  The tunnel
    # adds run-to-run noise of up to ~30%, so every throughput number is the
    # best of 3 timed blocks (the block itself is a single device dispatch).
    gbdt.train_iters(TREES)
    sync()

    dt = 1e30
    for _ in range(3):
        t0 = time.time()
        gbdt.train_iters(TREES)
        sync()
        dt = min(dt, time.time() - t0)
    row_trees_per_s = N * TREES / dt / 1e6

    # the reference's own policy: leaf-wise (best-first), wave-batched
    # schedule with smaller-child subtraction (models/grower_wave.py), at
    # the default bf16x2 histogram precision.  bf16 single-pass histograms
    # are ~25% faster at 100-iter AUC parity but measurably lose AUC by
    # 500 iterations (0.9095 vs 0.9126 measured round 4), so the headline
    # stays at the precision that BEATS the reference's quality.
    cfg_lw = Config.from_dict({**{k: getattr(cfg, k) for k in (
        "objective", "num_leaves", "max_bin", "learning_rate",
        "min_data_in_leaf", "metric")}, "verbosity": -1,
        "tree_growth": "leafwise"})
    gb_lw = create_boosting(cfg_lw, ds)
    gb_lw.add_valid(dt_test, "test")
    lw_trees = TREES
    gb_lw.train_iters(lw_trees)
    jax.device_get(gb_lw._train_scores.score)
    lw_dt = 1e30
    for _ in range(3):
        t0 = time.time()
        gb_lw.train_iters(lw_trees)
        jax.device_get(gb_lw._train_scores.score)
        lw_dt = min(lw_dt, time.time() - t0)
    leafwise_mrt = N * lw_trees / lw_dt / 1e6
    remaining_lw = max(AUC_ITERS - gb_lw.iter, 0)
    if remaining_lw:
        gb_lw.train_iters(remaining_lw)
        jax.device_get(gb_lw._train_scores.score)
    leafwise_auc = None
    for (_, name, value, _) in gb_lw.eval_valid():
        if name == "auc":
            leafwise_auc = float(value)

    # quality: continue to AUC_ITERS total trees, eval held-out AUC
    remaining = max(AUC_ITERS - gbdt.iter, 0)
    if remaining:
        gbdt.train_iters(remaining)
        sync()
    auc = None
    for (_, name, value, _) in gbdt.eval_valid():
        if name == "auc":
            auc = float(value)
    # reference LightGBM (C++ CLI built from /root/reference, run on THIS
    # host, leaf-wise, same synthetic data/config): valid AUC and throughput
    # re-measured 2026-07-30 (round 4; machine idle, metric_freq=500 so the
    # timing is training-only like ours): 100 iters in 25.57 s, 500 iters in
    # 93.23 s train wall-clock.  Round 3's recorded 2.360 M row-trees/s is
    # superseded — the host was evidently contended then.
    auc_ref = 0.913227          # reference valid_1 auc at iteration 100
    ref_same_host_mrt = 3.911   # reference M row-trees/s, first 100 iters
    ref_500_wall_s = 93.23      # reference 500-iter training wall-clock
    ref_500_auc = 0.912632      # reference valid_1 auc at iteration 500

    extra = {}
    if backend != "cpu" and os.environ.get("BENCH_FULL", "1") == "1":
        try:
            extra.update(measure_hist_and_roofline(ds, N))
        except Exception as e:  # noqa: BLE001 — partial records beat none
            extra["hist_error"] = f"{type(e).__name__}: {e}"[:200]

        # DART per-iteration cost (fused single-dispatch iteration):
        # VERDICT r3 #7 asks this within ~2x of the scanned GBDT path
        try:
            cfg_dart = Config.from_dict({
                "objective": "binary", "boosting": "dart", "num_leaves": 255,
                "max_bin": 63, "learning_rate": 0.1, "min_data_in_leaf": 20,
                "drop_rate": 0.1, "verbosity": -1,
                "tree_growth": "leafwise"})
            gbd = create_boosting(cfg_dart, ds)
            for _ in range(8):   # warm the no-drop and P-bucket variants
                gbd.train_one_iter(check_stop=False)
            sync_d = lambda: jax.device_get(gbd._train_scores.score)
            sync_d()
            DIT = 15
            t0 = time.time()
            for _ in range(DIT):
                gbd.train_one_iter(check_stop=False)
            sync_d()
            dart_dt = time.time() - t0
            dart_mrt = N * DIT / dart_dt / 1e6
            extra["dart_M_row_trees_per_s"] = round(dart_mrt, 3)
            extra["dart_frac_of_scanned_gbdt"] = round(
                dart_mrt / max(row_trees_per_s, 1e-9), 3)
        except Exception as e:  # noqa: BLE001
            extra["dart_error"] = f"{type(e).__name__}: {e}"[:200]

        # 500-tree north star (docs/Experiments.rst:110-135 methodology on
        # this host's data): reference side measured with the same binary
        # the goldens use; our side timed over trees 100..500 (the first
        # 100 run under compile) and scaled to 500
        try:
            gb5 = create_boosting(cfg_lw, ds)
            gb5.add_valid(dt_test, "test")
            gb5.train_iters(100)
            jax.device_get(gb5._train_scores.score)
            t0 = time.time()
            for _ in range(4):
                gb5.train_iters(100)
            jax.device_get(gb5._train_scores.score)
            wall400 = time.time() - t0
            wall500 = wall400 * 500.0 / 400.0
            auc500 = None
            for (_, name, value, _) in gb5.eval_valid():
                if name == "auc":
                    auc500 = float(value)
            extra["tpu_500iter_wall_s"] = round(wall500, 2)
            extra["tpu_500iter_auc"] = (round(auc500, 6)
                                        if auc500 is not None else None)
            extra["ref_cpp_500iter_wall_s"] = ref_500_wall_s
            extra["ref_cpp_500iter_auc"] = ref_500_auc
            extra["vs_ref_500iter"] = round(ref_500_wall_s / wall500, 4)
        except Exception as e:  # noqa: BLE001
            extra["northstar_error"] = f"{type(e).__name__}: {e}"[:200]

    baseline = 10.5e6 * 500 / 130.094 / 1e6   # reference CPU HIGGS throughput
    print(json.dumps({
        # headline = leaf-wise (the reference's own growth policy), bf16
        # device histograms (the reference's GPU-benchmark precision choice)
        "metric": f"higgs-shaped binary training throughput, leaf-wise "
                  f"({backend}, {N} rows, 28 feat, 63 bins, 255 leaves)",
        "value": round(leafwise_mrt, 3),
        "unit": "M row-trees/s",
        "vs_baseline": round(leafwise_mrt / baseline, 4),
        "auc": (round(leafwise_auc, 5)
                if leafwise_auc is not None else None),
        "auc_ref_lightgbm_cpp": auc_ref,
        # auc_iters fields record the ACTUAL tree counts behind each auc —
        # with BENCH_TREES overridden high the timed blocks can overshoot
        # AUC_ITERS, making the ref comparison no longer like-for-like
        "auc_iters": int(gb_lw.iter),
        # the reference C++ CLI measured on THIS host's CPU (the 40.36 M
        # row-trees/s baseline machine is a 28-core dual-Xeon; see PERF.md)
        "ref_cpp_same_host_M_row_trees_per_s": ref_same_host_mrt,
        "vs_ref_same_host": round(leafwise_mrt / ref_same_host_mrt, 4),
        "levelwise_M_row_trees_per_s": round(row_trees_per_s, 3),
        "levelwise_auc": round(auc, 5) if auc is not None else None,
        "levelwise_auc_iters": int(gbdt.iter),
        "levelwise_vs_ref_same_host": round(
            row_trees_per_s / ref_same_host_mrt, 4),
        "train_seconds_for_timed_block": round(lw_dt, 3),
        **extra,
    }))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:   # noqa: BLE001 — the driver records stdout; a
        # crash must still leave a parseable record of what happened
        import traceback

        traceback.print_exc()
        print(json.dumps({
            "metric": "higgs-shaped binary training throughput (FAILED)",
            "value": 0.0,
            "unit": "M row-trees/s",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}"[:300],
        }))
        sys.exit(1)   # truthful exit code alongside the parseable record
