"""Benchmark: HIGGS-shaped binary training throughput + AUC on one chip.

Reference baseline (BASELINE.md / docs/Experiments.rst:110-124): LightGBM
trains HIGGS (10.5M rows x 28 features, num_leaves=255) at 500 trees /
130.094 s on 2x Xeon E5-2690 v4 = **40.36M row-trees/s**.  The GPU-learner
benchmark config (docs/GPU-Performance.rst:108-124) uses max_bin=63; we
follow the GPU config for bins since that is the device-offload comparison
point.

This bench trains on a synthetic HIGGS-shaped dataset (same feature count,
bins, leaves) sized to this chip and reports:

    value       = trained rows*trees per second (millions), measured with a
                  full device sync (jax.device_get) — NOT block_until_ready,
                  which does not synchronize through the axon tunnel
    vs_baseline = value / 40.36   (>1 means faster than the reference CPU)
    auc         = held-out AUC after `auc_iters` total trees
    auc_ref     = reference LightGBM (C++, leaf-wise) AUC on the SAME data
                  and config, recorded from a run of the reference binary

See PERF.md for measured ceilings of the benchmarked device.  The chip is
reached through a network tunnel with ~113 ms round-trip dispatch latency,
so everything is measured with multi-iteration scanned steps (one dispatch
per timed block); compute-wise the tunneled chip profiles near physical
v5e rates once dispatch is amortized (tools/microbench_hist.py measures
the device matmul peak used for the roofline fraction below).
vs_baseline compares against the 2x-Xeon HIGGS number from
docs/Experiments.rst; vs_ref_same_host against the reference C++ binary
run on THIS host — the like-for-like comparison.

Prints exactly one JSON line.
"""

import json
import os
import sys
import time

import numpy as np


def make_data(n, seed):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 28).astype(np.float32)
    logit = (X[:, 0] * 1.2 - X[:, 1] + 0.6 * X[:, 2] * X[:, 3]
             + 0.4 * X[:, 4] + 0.3 * np.sin(3.0 * X[:, 5]))
    y = (logit + rng.randn(n).astype(np.float32) > 0).astype(np.float64)
    return X, y


def make_multiclass_data(n, seed, n_class=5, f=28):
    """Synthetic multiclass set for the parity block (the reference's
    Experiments.rst multiclass rows use proprietary Allstate/Yahoo data —
    not downloadable here, zero egress; shapes follow the binary block)."""
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    # label function fixed across train/valid splits (centers must NOT
    # depend on the split seed)
    centers = np.random.RandomState(12345).randn(n_class, f) \
        .astype(np.float32) * 0.6
    logits = X @ centers.T
    logits[:, 0] += 0.8 * X[:, 0] * X[:, 1]
    logits[:, 1] += 0.6 * np.sin(2.0 * X[:, 2])
    logits += rng.randn(n, n_class).astype(np.float32) * 1.5
    y = logits.argmax(axis=1).astype(np.float64)
    return X, y


def make_rank_data(n_query, docs, seed, f=64):
    """MSLR-WEB30K-shaped synthetic ranking set: fixed-size queries,
    graded relevance 0..4 by within-query score quantiles (the reference's
    MS-LTR rows, docs/Experiments.rst:113-151)."""
    rng = np.random.RandomState(seed)
    n = n_query * docs
    X = rng.randn(n, f).astype(np.float32)
    score = (X[:, 0] + 0.6 * X[:, 1] * X[:, 2] - 0.4 * X[:, 3]
             + 0.3 * np.sin(2.0 * X[:, 4])
             + rng.randn(n).astype(np.float32) * 1.2)
    s = score.reshape(n_query, docs)
    ranks = s.argsort(axis=1).argsort(axis=1) / (docs - 1)
    y = np.digitize(ranks.reshape(-1), [0.5, 0.75, 0.9, 0.97]) \
        .astype(np.float64)
    group = np.full(n_query, docs, dtype=np.int64)
    return X, y, group


# Reference C++ CLI on THIS host: multiclass / lambdarank parity blocks,
# same synthetic data (identical generator + seed via
# tools/measure_ref_parity.py), same config, 1 core, idle machine,
# training-only timing (process wall minus logged data-load time,
# metric_freq=<iters> so eval cost is excluded).  Measured 2026-07-31
# (round 5): multiclass 250k rows x 28 feat x 5 classes, 127 leaves,
# 50 iters -> 13.5 s; lambdarank 2000x100 docs, 64 feat, 63 leaves,
# 100 iters -> 12.2 s.
REF_MC_M_ROW_TREES_S = 4.619
REF_MC_LOGLOSS = 0.830193
REF_RK_M_ROW_TREES_S = 1.635
REF_RK_NDCG10 = 0.613977
# Reference CLI `task=predict` on the 1M-row binary bench set with the
# 100-tree model, file->file (data parse + predict + result write), 1
# core, idle host — measured by tools/measure_ref_parity.py's predict
# block.  None until the next idle-host session records it; the bench
# emits our side regardless so the comparison lands the moment the
# constant does.
REF_PREDICT_M_ROWS_S = None


def timed_per_rep(make_reps, r1, r2):
    """Per-rep seconds from a TWO-length-scan differential: wall(r2) -
    wall(r1) over (r2 - r1) reps cancels dispatch latency and other
    per-call fixed costs (the ~113 ms tunnel round-trip would otherwise
    dominate and overstate per-rep time severalfold).  Thin wrapper over
    the shared helper so this file, tools/phase_attrib.py and the tests
    all run the SAME methodology (median of interleaved pairs, device_get
    sync)."""
    from lightgbmv1_tpu.utils.timer import scan_differential_ms

    return scan_differential_ms(make_reps, r1, r2) / 1e3


def estimated_wave_schedule(K=None, budget=254):
    """Frontier-doubling estimate (1,2,4,..,K then sustained K) — the
    fallback when the round probe cannot run, always flagged
    `wave_rounds_estimated` in the record."""
    if K is None:
        from lightgbmv1_tpu.models.grower_wave import auto_wave_size

        K = auto_wave_size(255)
    rounds, splits, k = [], 0, 1
    while splits < budget:
        rounds.append(min(k, budget - splits))
        splits += rounds[-1]
        k = min(2 * k, K)
    return {"schedule": rounds, "rounds_per_tree": len(rounds),
            "estimated": True}


def probe_round_schedule(model, n_trees=5, K=None):
    """ACTUAL wave-round schedule per tree (VERDICT r4 weak #2: the old
    record derived hist_ms_per_iter from an assumed 4 rounds/tree; the
    frontier RAMPS 1,2,4,... so a 255-leaf tree takes ~10-11).  Replayed
    EXACTLY from trees the bench already trained — their recorded
    structure + gains determine the executed round grouping
    (grower_wave.replay_wave_schedule; the axon runtime cannot run
    jax.debug callbacks, and the replay needs no device round-trip at
    all).  A CPU test pins replay == the live _ROUND_PROBE counts."""
    from lightgbmv1_tpu.models.grower_wave import (auto_wave_size,
                                                    replay_wave_schedule)

    if K is None:   # the bench config leaves leafwise_wave_size on auto
        K = auto_wave_size(255)
    trees = model.materialize_host_trees()[:n_trees]
    scheds = [s for s in replay_wave_schedule(trees, K) if s]
    if not scheds:
        return None
    rounds = [k for s in scheds for k in s]
    return {"schedule": rounds,
            "rounds_per_tree": len(rounds) / len(scheds)}


def measure_hist_and_roofline(ds, N, schedule=None):
    """Measured feature-histogram pass times + roofline fraction — the
    BASELINE.json tracked metric ("feature-histogram build ms/iter") and
    the evidence behind PERF.md's kernel-quality claim.  Methodology of
    docs/GPU-Performance.rst:108-124 (time the device histogram kernel on
    the benchmark config), plus a same-session matmul peak measurement so
    the roofline fraction compares against THIS device's real ceiling.
    Every number is from R reps inside one jit scan (one dispatch), with
    per-rep input perturbation to defeat CSE.

    ``hist_ms_per_iter`` is derived from the PROBED round schedule: each
    round's pass is priced at its slot bucket's measured time (the wave
    grower runs sliced 4/16/64-slot variants), plus the 1-slot root pass.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from lightgbmv1_tpu.models.grower_wave import (auto_wave_size,
                                                    slot_buckets_for)
    from lightgbmv1_tpu.ops.histogram import default_hist_method, hist_wave

    K = auto_wave_size(255)   # the wave grower's auto K (= 63) at 255 leaves
    BUCKETS = tuple(slot_buckets_for(K, N))   # single source of truth
    B = 64                # padded bin axis for max_bin=63
    binned = jnp.asarray(ds.train_matrix)
    F = binned.shape[0]
    rng = np.random.RandomState(7)
    g3 = jnp.asarray(rng.randn(N, 3).astype(np.float32))
    method = default_hist_method("auto", binned.dtype)

    def hist_make_for(slots, precision):
        label = jnp.asarray(
            rng.randint(0, slots, size=N).astype(np.int32))

        def hist_make(r):
            @jax.jit
            def reps():
                def body(c, i):
                    g = g3 * (1.0 + 1e-6 * i.astype(jnp.float32))
                    h = hist_wave(binned, g, label, slots, B, method=method,
                                  precision=precision)
                    return c + h.sum(), None
                s, _ = lax.scan(body, jnp.float32(0), jnp.arange(r))
                return s
            return reps
        return hist_make

    # price each bucket at the precision TRAINING actually uses there:
    # sustained (largest-bucket) rounds run the deep dtype (single-pass
    # bf16 under the default policy, parallel/trainer.py), ramp rounds and
    # the root keep bf16x2 — pricing everything at bf16x2 would overstate
    # phase_hist_ms by ~2x on the sustained rounds
    pass_ms = {}
    for slots in (1,) + BUCKETS:
        # mirror the grower's deep gate exactly (grower_wave round_pass:
        # S == K and K >= 32 and bucketing active) so pricing cannot
        # drift from what training runs
        deep = slots == K and K >= 32 and len(BUCKETS) > 1
        pass_ms[slots] = timed_per_rep(
            hist_make_for(slots, "bf16" if deep else "bf16x2"), 4, 16) * 1e3

    # the int8sr precision variant (hist_dtype_deep="int8sr",
    # ops/quantize.py): price the quantized pass at the two buckets the
    # grower's gate makes eligible — the sustained K bucket and the
    # 16-slot ramp bucket — INCLUDING the stochastic-rounding quantization
    # itself (the honest per-pass cost the gate decision rides on)
    quant_fields = {}
    try:
        from lightgbmv1_tpu.ops.histogram import hist_wave_quant

        key0 = jax.random.PRNGKey(0)

        def quant_make_for(slots):
            label = jnp.asarray(
                rng.randint(0, slots, size=N).astype(np.int32))

            def make(r):
                @jax.jit
                def reps():
                    def body(c, i):
                        g = g3 * (1.0 + 1e-6 * i.astype(jnp.float32))
                        h, sc = hist_wave_quant(
                            binned, g, label, slots, B,
                            jax.random.fold_in(key0, i), method=method)
                        return c + h.sum() * sc[0, 0], None
                    s, _ = lax.scan(body, jnp.float32(0), jnp.arange(r))
                    return s
                return reps
            return make

        quant_fields["hist_ms_per_pass_int8sr"] = round(
            timed_per_rep(quant_make_for(K), 4, 16) * 1e3, 2)
        if 16 in BUCKETS and 16 != K:
            quant_fields["hist_ms_per_pass_s16_int8sr"] = round(
                timed_per_rep(quant_make_for(16), 4, 16) * 1e3, 2)
    except Exception as e:  # noqa: BLE001 — variant row must not kill hist
        quant_fields["int8sr_error"] = f"{type(e).__name__}: {e}"[:200]

    # the roofline fraction grades the KERNEL at full bf16x2 (2 MXU
    # passes), independent of the training-time deep-precision policy
    per_pass = timed_per_rep(hist_make_for(K, "bf16x2"), 4, 16)
    out_full_pass_ms = per_pass * 1e3
    # one-hot MXU formulation: (3*(K+1), rows) @ (rows, B*F) per pass,
    # bf16x2 = 2 passes (ops/hist_pallas.py)
    hist_flops = 2 * 3 * (K + 1) * N * B * F * 2
    hist_tfs = hist_flops / per_pass / 1e12

    # device matmul peak, same session, same measurement discipline
    M = 4096
    a = jnp.asarray(rng.randn(M, M).astype(np.float32), jnp.bfloat16)
    b = jnp.asarray(rng.randn(M, M).astype(np.float32), jnp.bfloat16)

    def mm_make(r):
        @jax.jit
        def reps():
            def body(c, i):
                out = jnp.dot(a * (1 + 1e-3 * i.astype(jnp.bfloat16)), b,
                              preferred_element_type=jnp.float32)
                return c + out.sum(), None
            s, _ = lax.scan(body, jnp.float32(0), jnp.arange(r))
            return s
        return reps

    peak_tfs = (2 * M ** 3) / timed_per_rep(mm_make, 8, 64) / 1e12

    def bucket_of(k):
        for s in BUCKETS:
            if k <= s:
                return s
        return K

    out = {
        # the BASELINE-tracked kernel pass at full bf16x2 precision
        "hist_ms_per_pass": round(out_full_pass_ms, 2),
        # the sustained-round pass as TRAINING runs it (deep bf16 policy)
        "hist_ms_per_pass_deep": round(pass_ms[K], 2),
        "hist_ms_per_pass_root": round(pass_ms[1], 2),
        "hist_achieved_tf_s": round(hist_tfs, 2),
        "device_matmul_peak_tf_s": round(peak_tfs, 2),
        "hist_roofline_frac": round(hist_tfs / peak_tfs, 4),
    }
    out.update(quant_fields)
    for s in BUCKETS[:-1]:   # ramp buckets exist only when bucketing is on
        out[f"hist_ms_per_pass_s{s}"] = round(pass_ms[s], 2)
    if schedule:
        rounds = schedule["schedule"]
        iters = max(1, round(len(rounds) / schedule["rounds_per_tree"]))
        if schedule.get("estimated"):
            out["wave_rounds_estimated"] = True
    else:
        est = estimated_wave_schedule(K)
        rounds, iters = est["schedule"], 1
        out["wave_rounds_estimated"] = True
    per_iter = (sum(pass_ms[bucket_of(k)] for k in rounds) / iters
                + pass_ms[1])
    out["wave_rounds_per_tree"] = round(len(rounds) / iters, 2)
    out["hist_ms_per_iter"] = round(per_iter, 2)
    return out


def measure_phases(ds, N, gb_lw, schedule, hist_fields, n_valid,
                   per_iter_ms):
    """Per-phase ms/iter breakdown (VERDICT r4 #3) — the role of the
    reference's USE_TIMETAG global timer printout
    (include/LightGBM/utils/common.h:1054-1138).

    Each phase op is timed with the two-length-scan differential at the
    bench shapes and priced over the PROBED round schedule:
      hist        — from measure_hist_and_roofline (per-bucket passes)
      partition   — the (S, N) decision pass (bin reads + compares + the
                    leaf-id/label reductions), per bucket, train rows
      valid_route — the same pass over the attached valid set's rows
      split       — the vmapped 2K-child find_best_split scan
      other       — residual vs the measured per-iteration wall (top-k,
                    tree assembly scatters, scan/while overheads)
    The partition/split ops are re-created at bench shapes from the same
    modules the grower uses; 'other' being a residual is what keeps the
    decomposition honest against the measured total."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from lightgbmv1_tpu.models.grower_wave import (auto_wave_size,
                                                    slot_buckets_for)
    from lightgbmv1_tpu.ops.split import NO_CONSTRAINT, find_best_split

    B = 64
    K = auto_wave_size(255)
    BUCKETS = tuple(slot_buckets_for(K, N))
    binned = jnp.asarray(ds.train_matrix)
    F = binned.shape[0]
    L = 255
    rng = np.random.RandomState(11)
    rounds = schedule["schedule"]
    iters = max(1, round(len(rounds) / schedule["rounds_per_tree"]))

    def bucket_of(k):
        for s in BUCKETS:
            if k <= s:
                return s
        return K

    def part_make_for(S, rows):
        lids = jnp.asarray(rng.randint(0, L, size=rows).astype(np.int32))
        feats = jnp.asarray(rng.randint(0, F, size=S).astype(np.int32))
        thrs = jnp.asarray(rng.randint(0, B, size=S).astype(np.int32))
        leafs = jnp.asarray(rng.randint(0, L, size=S).astype(np.int32))
        nls = leafs + 1
        sml = jnp.asarray(rng.rand(S) < 0.5)
        siota = jnp.arange(S, dtype=jnp.int32)
        mat = binned[:, :rows]

        def make(r):
            @jax.jit
            def reps():
                def body(c, i):
                    fk = (feats + i) % F
                    bk = jax.vmap(lambda f: mat[f])(fk).astype(jnp.int32)
                    gl = bk <= thrs[:, None]
                    mine = lids[None, :] == leafs[:, None]
                    upd = jnp.sum(jnp.where(
                        mine & (~gl), nls[:, None] - lids[None, :], 0),
                        axis=0)
                    lab = jnp.sum(jnp.where(
                        mine & (gl == sml[:, None]), siota[:, None] - S, 0),
                        axis=0) + S
                    return c + upd.sum() + lab.sum(), None
                s, _ = lax.scan(body, jnp.int32(0), jnp.arange(r))
                return s
            return reps
        return make

    part_ms = {s: timed_per_rep(part_make_for(s, N), 4, 16) * 1e3
               for s in BUCKETS}
    partv_ms = {s: timed_per_rep(part_make_for(s, n_valid), 4, 16) * 1e3
                for s in BUCKETS} if n_valid else {s: 0.0 for s in BUCKETS}

    meta = gb_lw.meta
    params = gb_lw.split_params
    h2k = jnp.asarray(
        np.abs(rng.randn(2 * K, F, B, 3)).astype(np.float32))
    parents = h2k[:, 0].sum(axis=1)                    # (2K, 3)
    mask = jnp.ones(F, bool)
    nc = jnp.asarray(NO_CONSTRAINT, jnp.float32)

    def split_make(r):
        @jax.jit
        def reps():
            def body(c, i):
                h = h2k * (1.0 + 1e-6 * i.astype(jnp.float32))
                res = jax.vmap(
                    lambda hh, pp: find_best_split(
                        hh, pp, meta, mask, params, nc, 1, 0.0, 0.0,
                        None, None))(h, parents)
                return c + res.gain.sum(), None
            s, _ = lax.scan(body, jnp.float32(0), jnp.arange(r))
            return s
        return reps

    # the split scan is small (hundreds of k elements); high rep counts
    # keep the differential above tunnel noise (at 2/8 reps it measured 0)
    split_round_ms = timed_per_rep(split_make, 8, 64) * 1e3

    hist_iter = hist_fields.get("hist_ms_per_iter", 0.0)
    part_iter = sum(part_ms[bucket_of(k)] for k in rounds) / iters
    partv_iter = sum(partv_ms[bucket_of(k)] for k in rounds) / iters
    split_iter = split_round_ms * len(rounds) / iters
    other = per_iter_ms - hist_iter - part_iter - partv_iter - split_iter
    return {
        "phase_hist_ms": round(hist_iter, 2),
        "phase_partition_ms": round(part_iter, 2),
        "phase_valid_route_ms": round(partv_iter, 2),
        "phase_split_ms": round(split_iter, 2),
        "phase_other_ms": round(other, 2),
        "phase_total_measured_ms": round(per_iter_ms, 2),
    }


def measure_fused(ds, N, backend, n_iters):
    """``hist_method=fused`` A/B (ISSUE 13 — ops/wave_fused.py), every
    backend:

    * **parity** — trees of the fused run must byte-compare to the
      staged ``hist_method=pallas`` run's model text at the bench
      config (the same histogram arithmetic, fused vs staged
      scheduling; on CPU both ride the Pallas interpreter — the lane
      tests/test_wave_fused.py pins).
    * **throughput** — the fused run's M row-trees/s next to the
      headline.
    * **HBM accounting** — the compiled executables' own
      ``cost_analysis()`` bytes for ONE sustained-bucket wave round:
      staged (hist pass → subtraction → vmapped split scan) minus fused
      (one kernel, residue out).  ``fused_hbm_bytes_saved_per_round``
      is that difference — the measured form of the "the (F, B, 3)
      histogram stack never materializes off-chip" claim, with the
      analytic stack size recorded beside it for scale.

    ``fused_ok`` itself is joined in main(): parity AND (on device) the
    measured fused round <= staged ``phase_hist_ms + phase_split_ms``.
    """
    import jax
    import jax.numpy as jnp

    from lightgbmv1_tpu.basic import _objective_string
    from lightgbmv1_tpu.config import Config
    from lightgbmv1_tpu.io.model_text import model_to_string
    from lightgbmv1_tpu.models.gbdt import create_boosting

    fields = {}
    base = {
        "objective": "binary", "num_leaves": 255, "max_bin": 63,
        "learning_rate": 0.1, "min_data_in_leaf": 20, "verbosity": -1,
        "tree_growth": "leafwise",
    }

    def run(hist_method):
        cfg = Config.from_dict({**base, "hist_method": hist_method})
        gb = create_boosting(cfg, ds)
        gb.train_iters(n_iters)
        jax.device_get(gb._train_scores.score)
        dt = 1e30
        for _ in range(2):
            t0 = time.time()
            gb.train_iters(n_iters)
            jax.device_get(gb._train_scores.score)
            dt = min(dt, time.time() - t0)
        text = model_to_string(
            gb.materialize_host_trees(),
            objective_string=_objective_string(cfg), num_class=1,
            num_tree_per_iteration=1,
            feature_names=list(ds.feature_names),
            feature_infos=ds.feature_infos())
        return gb, dt, text

    gb_fu, fu_dt, fu_text = run("fused")
    _, st_dt, st_text = run("pallas")
    fields["fused_parity_ok"] = bool(fu_text == st_text)
    fields["fused_M_row_trees_per_s"] = round(N * n_iters / fu_dt / 1e6, 3)
    fields["fused_staged_pallas_M_row_trees_per_s"] = round(
        N * n_iters / st_dt / 1e6, 3)

    # analytic single-read contract (ISSUE 15) — pure shape arithmetic,
    # recorded even when the compile leg below cannot run: the routed
    # round touches the binned matrix once (F*N kernel sweep + N
    # decision bins) vs the staged partition's K-row gather + hist read
    from lightgbmv1_tpu.models.grower_wave import auto_wave_size

    F_b = ds.train_matrix.shape[0]
    K_b = auto_wave_size(255)
    fields["staged_round_binned_bytes_analytic"] = int(F_b * N + K_b * N)
    fields["fused_round_binned_bytes_analytic"] = int(F_b * N + N)
    fields["fused_round_single_read_ok"] = bool(
        fields["fused_round_binned_bytes_analytic"]
        < fields["staged_round_binned_bytes_analytic"])

    # ---- compiled-executable HBM accounting (cost_analysis bytes) ------
    # own guard region: a backend that cannot lower (or cost-analyze)
    # the round executables must not take the parity fields down with it
    try:
        fields.update(_fused_round_bytes(ds, N, backend, gb_fu))
    except Exception as e:  # noqa: BLE001
        fields["fused_bytes_error"] = f"{type(e).__name__}: {e}"[:200]
    return fields


def measure_fused_waveloop(ds, N, backend, n_iters):
    """Persistent multi-round wave loop A/B (ISSUE 17 —
    ``wave_loop_rounds`` on the ``hist_method=fused`` path), every
    backend:

    * **parity** — the looped run's trees must byte-compare to the
      single-round fused run's model text (which measure_fused pins
      against staged): the R-rounds-per-launch kernel replays the same
      round boundary, so this is the whole-loop bit contract.
    * **launch accounting** — the VMEM plan (recorded verbatim: why this
      shape looped or fell back) and the analytic launch/state-traffic
      deltas: each R-round segment saves R-1 kernel launches and R-1
      round-trips of the resident state (frontier table + leaf ids +
      hist pool — ``2 * state_bytes`` per avoided boundary).
    * **measured bytes** — the compiled ``grow.fused_loop`` vs
      ``grow.fused_round`` executables' own cost_analysis bytes
      (obs/xla compile telemetry), the measured form of "state never
      spills", recorded beside the analytic figure.
    * **``phase_wave_loop_ms``** — the looped run's per-iteration round
      dispatch ms by the differential method: the single-round run's
      per-iter wall minus the looped run's per-iter wall is the
      boundary saving; applied to the single-round wall it prices the
      loop dispatch as a phase row (bench_trend watches it at the 10%
      bar on device captures).

    ``fused_loop_ok`` is joined in main(): parity everywhere AND, on
    device, loop per-iter <= single-round per-iter.
    """
    import jax

    from lightgbmv1_tpu.basic import _objective_string
    from lightgbmv1_tpu.config import Config
    from lightgbmv1_tpu.io.model_text import model_to_string
    from lightgbmv1_tpu.models.gbdt import create_boosting
    from lightgbmv1_tpu.models.grower_wave import (_SUB_STATE_CAP_BYTES,
                                                   auto_wave_size,
                                                   slot_buckets_for)
    from lightgbmv1_tpu.obs import xla as obs_xla
    from lightgbmv1_tpu.ops.wave_fused import plan_wave_loop

    fields = {}
    R_REQ = 4
    base = {
        "objective": "binary", "num_leaves": 255, "max_bin": 63,
        "learning_rate": 0.1, "min_data_in_leaf": 20, "verbosity": -1,
        "tree_growth": "leafwise", "hist_method": "fused",
    }

    def run(over):
        cfg = Config.from_dict({**base, **over})
        gb = create_boosting(cfg, ds)
        gb.train_iters(n_iters)
        jax.device_get(gb._train_scores.score)
        dt = 1e30
        for _ in range(2):
            t0 = time.time()
            gb.train_iters(n_iters)
            jax.device_get(gb._train_scores.score)
            dt = min(dt, time.time() - t0)
        text = model_to_string(
            gb.materialize_host_trees(),
            objective_string=_objective_string(cfg), num_class=1,
            num_tree_per_iteration=1,
            feature_names=list(ds.feature_names),
            feature_infos=ds.feature_infos())
        return gb, dt, text

    gb_lp, lp_dt, lp_text = run({"wave_loop_rounds": R_REQ})
    _, sr_dt, sr_text = run({})
    fields["fused_loop_parity_ok"] = bool(lp_text == sr_text)
    fields["wave_loop_M_row_trees_per_s"] = round(
        N * n_iters / lp_dt / 1e6, 3)
    fields["wave_loop_single_round_M_row_trees_per_s"] = round(
        N * n_iters / sr_dt / 1e6, 3)

    # the static plan, recorded verbatim (why this shape looped or fell
    # back) + the analytic launch / state-traffic deltas it implies
    F_b = int(ds.train_matrix.shape[0])
    K_b = auto_wave_size(255)
    L_b = 255
    B_b = 64
    use_sub_b = L_b * F_b * B_b * 3 * 4 <= _SUB_STATE_CAP_BYTES
    plan = plan_wave_loop(
        rounds=R_REQ, N=N, F=F_b, num_bins=B_b, K=K_b, L=L_b,
        use_sub=use_sub_b, slot_buckets=slot_buckets_for(K_b, N))
    fields["fused_loop_plan"] = {k: (list(v) if isinstance(v, tuple)
                                     else v) for k, v in plan.items()}
    R_eff = plan["rounds"] if plan["eligible"] else 1
    fields["fused_loop_rounds"] = int(R_eff)
    fields["fused_loop_launches_saved_per_segment"] = int(R_eff - 1)
    fields["fused_loop_state_bytes_saved_per_segment_analytic"] = int(
        (R_eff - 1) * 2 * plan["state_bytes"])

    # measured executable bytes (obs/xla compile telemetry): the looped
    # vs single-round grow executables' own cost_analysis
    st = obs_xla.compile_stats()
    for label, key in (("grow.fused_loop", "fused_loop_bytes_accessed"),
                       ("grow.fused_round",
                        "fused_round_bytes_accessed")):
        b = (st.get(label) or {}).get("bytes_accessed")
        fields[key] = int(b) if b is not None else None

    # phase_wave_loop_ms by the differential method (device sessions:
    # the watched phase row; the CPU interpreter's wall is
    # unrepresentative, so the CPU record carries the raw per-iter ms
    # pair only, like fused_ok's perf leg)
    lp_it = lp_dt / n_iters * 1e3
    sr_it = sr_dt / n_iters * 1e3
    fields["wave_loop_ms_per_iter"] = round(lp_it, 3)
    fields["wave_loop_single_round_ms_per_iter"] = round(sr_it, 3)
    if backend != "cpu" and fields["fused_loop_rounds"] > 1:
        # joined into phase_wave_loop_ms in main(), where the
        # single-round dispatch ms (partition_fused_ms_per_iter) lives
        fields["wave_loop_boundary_saving_ms_per_iter"] = round(
            sr_it - lp_it, 3)
    return fields


def measure_packed(X, y, backend, n_iters):
    """``bin_layout=packed4`` A/B (ISSUE 18 — sub-byte bin residency),
    every backend, at its own ``max_bin=15`` config (the nibble regime):

    * **parity** — trees of the packed fused run must byte-compare to
      the unpacked fused AND staged runs' model text: the kernels
      unpack nibbles in VMEM onto the identical arithmetic, so packing
      is a pure storage-layout change (the lane
      tests/test_wave_fused.py pins across the golden matrix).
    * **analytic bytes** — the per-round binned HBM read halves:
      ``ceil(F/2) * N`` packed bytes vs ``F * N`` unpacked
      (``packed_binned_bytes``, watched by bench_trend on device
      captures); the acceptance bar is a >= 1.9x reduction.
    * **measured bytes** — the compiled histogram executables' own
      ``cost_analysis()`` bytes, packed vs unpacked input, recorded
      beside the analytic figure (CPU interpret-mode accounting is
      unrepresentative — ``packed_bytes_interpret_mode`` — like the
      fused round's byte leg).

    ``packed_ok`` is joined in main(): parity AND the analytic >= 1.9x
    reduction AND, on device, a measured hist-bytes reduction >= 1.5x.
    """
    import jax
    import jax.numpy as jnp

    from lightgbmv1_tpu.basic import _objective_string
    from lightgbmv1_tpu.config import Config
    from lightgbmv1_tpu.io.dataset import BinnedDataset
    from lightgbmv1_tpu.io.model_text import model_to_string
    from lightgbmv1_tpu.models.gbdt import create_boosting
    from lightgbmv1_tpu.obs.xla import _extract_cost
    from lightgbmv1_tpu.ops.hist_pallas import hist_leaves_pallas, pack4bit

    fields = {}
    interp = backend == "cpu"
    N = int(X.shape[0])
    base = {
        "objective": "binary", "num_leaves": 63, "max_bin": 15,
        "learning_rate": 0.1, "min_data_in_leaf": 20, "verbosity": -1,
        "tree_growth": "leafwise",
    }

    def run(over):
        cfg = Config.from_dict({**base, **over})
        ds = BinnedDataset.from_numpy(X, label=y, config=cfg)
        gb = create_boosting(cfg, ds)
        gb.train_iters(n_iters)
        jax.device_get(gb._train_scores.score)
        dt = 1e30
        for _ in range(2):
            t0 = time.time()
            gb.train_iters(n_iters)
            jax.device_get(gb._train_scores.score)
            dt = min(dt, time.time() - t0)
        text = model_to_string(
            gb.materialize_host_trees(),
            objective_string=_objective_string(cfg), num_class=1,
            num_tree_per_iteration=1,
            feature_names=list(ds.feature_names),
            feature_infos=ds.feature_infos())
        return ds, dt, text

    ds_u, _, st_text = run({"hist_method": "pallas"})
    _, u8_dt, u8_text = run({"hist_method": "fused"})
    _, pk_dt, pk_text = run({"hist_method": "fused",
                             "bin_layout": "packed4"})
    _, _, sp_text = run({"hist_method": "pallas",
                         "bin_layout": "packed4"})
    fields["packed_parity_ok"] = bool(
        pk_text == u8_text == st_text == sp_text)
    fields["packed_M_row_trees_per_s"] = round(N * n_iters / pk_dt / 1e6,
                                               3)
    fields["packed_u8_M_row_trees_per_s"] = round(
        N * n_iters / u8_dt / 1e6, 3)

    # analytic per-round binned read (uint8 bytes): the halving contract
    F = int(ds_u.num_features)
    Fp = -(-F // 2)
    fields["packed_binned_bytes"] = int(Fp * N)
    fields["unpacked_binned_bytes"] = int(F * N)
    fields["packed_binned_bytes_reduction"] = round(F / Fp, 3)

    # measured executable bytes: the staged histogram pass, packed vs
    # unpacked input, priced by the compiled executables themselves
    try:
        binned = jnp.asarray(ds_u.train_matrix)
        pb = jnp.asarray(pack4bit(np.asarray(ds_u.train_matrix)))
        rng = np.random.RandomState(13)
        g3 = jnp.asarray(rng.randn(N, 3).astype(np.float32))
        lids = jnp.asarray(rng.randint(0, 16, N).astype(np.int32))
        u8_c = jax.jit(lambda b, g, l: hist_leaves_pallas(
            b, g, l, 16, 16, precision="bf16x2",
            interpret=interp)).lower(binned, g3, lids).compile()
        pk_c = jax.jit(lambda b, g, l: hist_leaves_pallas(
            b, g, l, 16, 16, precision="bf16x2", interpret=interp,
            packed=True, num_features=F)).lower(pb, g3, lids).compile()
        _, ub = _extract_cost(u8_c)
        _, pbb = _extract_cost(pk_c)
        if ub and pbb:
            fields["packed_hist_bytes_accessed"] = int(pbb)
            fields["unpacked_hist_bytes_accessed"] = int(ub)
            fields["packed_hist_bytes_reduction"] = round(
                ub / max(pbb, 1), 3)
            # CPU smoke caveat: interpret mode lowers to plain XLA ops
            # with per-grid-step block copies — the byte comparison does
            # NOT reflect device behavior; the honest number is the
            # device capture's
            if interp:
                fields["packed_bytes_interpret_mode"] = True
    except Exception as e:  # noqa: BLE001 — the parity legs stand alone
        fields["packed_bytes_error"] = f"{type(e).__name__}: {e}"[:200]
    return fields


def _fused_round_bytes(ds, N, backend, gb_fu):
    """Compiled-executable byte accounting of ONE sustained wave round,
    BOTH legs starting from the same (leaf ids + committed splits)
    state (ISSUE 15): staged = the (S, N) partition decision pass +
    histogram pass + subtraction + vmapped split scan; fused = the
    routed single-pass kernel (partition + histogram + scan in one
    sweep of the binned rows) + the same per-leaf state update.  The
    analytic binned-traffic bound is recorded beside the measured
    figures: the fused round touches the binned matrix ONCE (F*N for
    the kernel sweep + N decision bins) where the staged round pays the
    hist read AND the partition's K-row gather + (K, N) HBM mask
    intermediates."""
    import jax
    import jax.numpy as jnp

    from lightgbmv1_tpu.models.grower_wave import (auto_wave_size,
                                                   subtract_child_hists)
    from lightgbmv1_tpu.obs.xla import _extract_cost
    from lightgbmv1_tpu.ops.histogram import hist_wave
    from lightgbmv1_tpu.ops.split import (NO_CONSTRAINT, find_best_split,
                                          go_left_rule)
    from lightgbmv1_tpu.ops.wave_fused import make_fused_round

    fields = {}
    interp = backend == "cpu"
    K = auto_wave_size(255)
    B = 64
    binned = jnp.asarray(ds.train_matrix)
    F = binned.shape[0]
    meta, params = gb_fu.meta, gb_fu.split_params
    rng = np.random.RandomState(13)
    L = 255
    g3 = jnp.asarray(rng.randn(N, 3).astype(np.float32))
    lids = jnp.asarray(rng.randint(0, K, N).astype(np.int32))
    feats = jnp.asarray(rng.randint(0, F, K).astype(np.int32))
    thrs = jnp.asarray(rng.randint(0, B, K).astype(np.int32))
    dls = jnp.asarray(rng.rand(K) < 0.5)
    leafs = jnp.arange(K, dtype=jnp.int32)
    nls = jnp.arange(K, dtype=jnp.int32) + K
    parent = jnp.asarray(
        np.abs(rng.randn(K, F, B, 3)).astype(np.float32)) * 4.0
    sml = jnp.asarray(rng.rand(K) < 0.5)
    csums = jnp.asarray(np.abs(rng.randn(2 * K, 3)).astype(np.float32))
    mask = jnp.ones((2 * K, F), bool)
    nc = jnp.asarray(NO_CONSTRAINT, jnp.float32)
    ar = jnp.arange(K, dtype=jnp.int32)
    siota = jnp.arange(K, dtype=jnp.int32)

    def staged_round(g3_, parent_, sml_):
        # the staged (S, N) partition decision pass (grower_wave
        # go_left_s): per-split bin gather + HBM mask intermediates
        bk = jax.vmap(lambda f: binned[f])(feats).astype(jnp.int32)
        gl = go_left_rule(bk, thrs[:, None], dls[:, None],
                          meta.missing_type[feats][:, None],
                          meta.nan_bin[feats][:, None],
                          meta.zero_bin[feats][:, None])
        mine = lids[None, :] == leafs[:, None]
        leaf_id = lids + jnp.sum(
            jnp.where(mine & (~gl), nls[:, None] - lids[None, :], 0),
            axis=0)
        label = jnp.sum(
            jnp.where(mine & (gl == sml_[:, None]),
                      siota[:, None] - K, 0), axis=0) + K
        h = hist_wave(binned, g3_, label, K, B, method="pallas",
                      precision="bf16x2", interpret=interp)
        hist, _, _ = subtract_child_hists(h, parent_, ar, ar, sml_,
                                          h_parent=parent_)
        res = jax.vmap(lambda hh, ps: find_best_split(
            hh, ps, meta, mask[0], params, nc, 1, 0.0, 0.0, None, None)
        )(hist, csums)
        return res.gain, res.feature, hist, leaf_id

    fn = make_fused_round(meta=meta, params=params, num_bins=B,
                          precision="bf16x2", deep_precision="bf16",
                          interpret=interp)
    route = dict(leaf_id=lids, feats=feats, thrs=thrs, dls=dls,
                 leafs=leafs, nls=nls, num_leaves=L)

    def fused_round(g3_, parent_, sml_):
        packed, hsm, _, leaf_id = fn(
            binned, g3_, None, K, mask=mask,
            csums=csums, constr=jnp.tile(nc, (2 * K, 1)),
            depth=jnp.ones(2 * K, jnp.int32),
            pout=jnp.zeros(2 * K, jnp.float32),
            sml=sml_, parent=parent_, route=route)
        # the per-leaf table update the grower still performs (the K
        # smaller-child stack IS emitted); keep it in the accounting so
        # the comparison prices the whole round fairly
        hist, _, _ = subtract_child_hists(hsm, parent_, ar, ar, sml_,
                                          h_parent=parent_)
        return packed, hist, leaf_id

    st_c = jax.jit(staged_round).lower(g3, parent, sml).compile()
    fu_c = jax.jit(fused_round).lower(g3, parent, sml).compile()
    _, st_bytes = _extract_cost(st_c)
    _, fu_bytes = _extract_cost(fu_c)
    # analytic binned-matrix traffic per round (uint8 bytes): the
    # single-read contract the acceptance criteria pin, recorded beside
    # whatever the compiled executables measure
    fields["staged_round_binned_bytes_analytic"] = int(F * N + K * N)
    fields["fused_round_binned_bytes_analytic"] = int(F * N + N)
    fields["fused_round_single_read_ok"] = bool(
        fields["fused_round_binned_bytes_analytic"]
        < fields["staged_round_binned_bytes_analytic"])
    if st_bytes and fu_bytes:
        fields["staged_round_bytes_accessed"] = int(st_bytes)
        fields["fused_round_bytes_accessed"] = int(fu_bytes)
        fields["fused_hbm_bytes_saved_per_round"] = int(
            st_bytes - fu_bytes)
        fields["fused_round_bytes_reduction"] = round(
            st_bytes / max(fu_bytes, 1), 3)
        # the analytic scan-stack size the fused path keeps on-chip
        fields["fused_hbm_stack_bytes_analytic"] = int(
            2 * K * F * B * 3 * 4)
        # CPU smoke caveat: in interpret mode the kernel lowers to plain
        # XLA ops with per-grid-step block copies, so the byte
        # comparison does NOT reflect device behavior (it typically
        # reads NEGATIVE there); the honest number is the device
        # capture's, where the kernel is one custom call and the VMEM
        # accumulator never appears in the byte accounting
        if interp:
            fields["fused_bytes_interpret_mode"] = True
    return fields


def measure_fused_round_ms(ds, N, gb_lw, schedule, hist_fields, backend):
    """The fused wave round timed per slot bucket with the two-length
    scan differential and priced over the REPLAYED round schedule —
    ``hist_split_fused_ms_per_iter``, directly comparable to
    ``phase_hist_ms + phase_split_ms`` (the staged root pass is added on
    both sides of that comparison: the fused path keeps the staged root
    histogram, so its cost rides this field via
    ``hist_ms_per_pass_root``).

    ISSUE 15: the ROUTED single-pass round (partition + valid-metadata
    decisions folded into the kernel, leaf ids in and out) is priced
    the same way as ``partition_fused_ms_per_iter`` — directly
    comparable to ``phase_hist_ms + phase_split_ms +
    phase_partition_ms``, the three staged traversals it collapses;
    bench_trend watches it at the 10% bar."""
    import jax
    import jax.numpy as jnp

    from lightgbmv1_tpu.models.grower_wave import (auto_wave_size,
                                                   slot_buckets_for)
    from lightgbmv1_tpu.ops.split import NO_CONSTRAINT
    from lightgbmv1_tpu.ops.wave_fused import make_fused_round

    B = 64
    K = auto_wave_size(255)
    BUCKETS = tuple(slot_buckets_for(K, N))
    binned = jnp.asarray(ds.train_matrix)
    F = binned.shape[0]
    rng = np.random.RandomState(14)
    g3 = jnp.asarray(rng.randn(N, 3).astype(np.float32))
    nc = jnp.asarray(NO_CONSTRAINT, jnp.float32)
    fn = make_fused_round(meta=gb_lw.meta, params=gb_lw.split_params,
                          num_bins=B, precision="bf16x2",
                          deep_precision="bf16",
                          interpret=backend == "cpu")

    def make_for(S, routed=False):
        label = jnp.asarray(rng.randint(0, S + 1, N).astype(np.int32))
        parent = jnp.asarray(
            np.abs(rng.randn(S, F, B, 3)).astype(np.float32)) * 4.0
        sml = jnp.asarray(rng.rand(S) < 0.5)
        csums = jnp.asarray(
            np.abs(rng.randn(2 * S, 3)).astype(np.float32))
        mask = jnp.ones((2 * S, F), bool)
        deep = S == K and K >= 32 and len(BUCKETS) > 1
        route = None
        if routed:
            route = dict(
                leaf_id=jnp.asarray(
                    rng.randint(0, S, N).astype(np.int32)),
                feats=jnp.asarray(
                    rng.randint(0, F, S).astype(np.int32)),
                thrs=jnp.asarray(rng.randint(0, B, S).astype(np.int32)),
                dls=jnp.asarray(rng.rand(S) < 0.5),
                leafs=jnp.arange(S, dtype=jnp.int32),
                nls=jnp.arange(S, dtype=jnp.int32) + S,
                num_leaves=255)

        def make(r):
            @jax.jit
            def reps():
                def body(c, i):
                    g = g3 * (1.0 + 1e-6 * i.astype(jnp.float32))
                    out = fn(
                        binned, g, None if routed else label, S,
                        deep=deep, mask=mask,
                        csums=csums, constr=jnp.tile(nc, (2 * S, 1)),
                        depth=jnp.ones(2 * S, jnp.int32),
                        pout=jnp.zeros(2 * S, jnp.float32),
                        sml=sml, parent=parent, route=route)
                    acc = out[0].sum() + out[1].sum()
                    if routed:   # the emitted leaf ids are a live output
                        acc = acc + out[3].sum().astype(jnp.float32)
                    return c + acc, None
                s, _ = jax.lax.scan(body, jnp.float32(0), jnp.arange(r))
                return s
            return reps
        return make

    pass_ms = {S: timed_per_rep(make_for(S), 4, 16) * 1e3
               for S in BUCKETS}
    routed_ms = {S: timed_per_rep(make_for(S, routed=True), 4, 16) * 1e3
                 for S in BUCKETS}

    def bucket_of(k):
        for s in BUCKETS:
            if k <= s:
                return s
        return K

    rounds = schedule["schedule"]
    iters = max(1, round(len(rounds) / schedule["rounds_per_tree"]))
    root_ms = hist_fields.get("hist_ms_per_pass_root", 0.0)
    per_iter = (sum(pass_ms[bucket_of(k)] for k in rounds) / iters
                + root_ms)
    routed_iter = (sum(routed_ms[bucket_of(k)] for k in rounds) / iters
                   + root_ms)
    out = {"hist_split_fused_ms_per_iter": round(per_iter, 2),
           "fused_ms_per_pass": round(pass_ms[K], 2),
           "partition_fused_ms_per_iter": round(routed_iter, 2),
           "partition_fused_ms_per_pass": round(routed_ms[K], 2)}
    for s in BUCKETS[:-1]:
        out[f"fused_ms_per_pass_s{s}"] = round(pass_ms[s], 2)
    return out


def measure_predict(gb_lw, X):
    """Prediction throughput, file->file (VERDICT r5 #6) — the role of the
    reference CLI's ``task=predict`` (src/application/predictor.hpp):
    parse the data file, predict every row with the trained ensemble,
    write the result file.  Three engines are timed on the SAME model and
    file:

    * the native C++ bulk predictor (lightgbmv1_tpu/native/predictor.cpp —
      per-row tree walks, OMP threads), reached through Booster.predict's
      big-batch routing,
    * the depth-stepped all-trees device walk (models/predict.py:
      prebinned serving codes, one (N,T) node-pointer array advanced
      max_depth times) — the serving engine this repo ships, and
    * the legacy per-tree scan walk (models/tree.ensemble_predict_raw) —
      the parity pin and the r05-era device figure the ``predict_ok``
      guard compares the new walk against.

    The device file->file window is split into its components
    (parse / prebin / H2D / walk / write) so transfer cost is no longer
    lumped into the compute rate: ``predict_device_compute_M_rows_per_s``
    is now the WALK-only rate.  ``predict_ok`` requires (a) node-exact
    leaf parity between the depth-stepped walk and the host reference and
    (b) the new walk at least matching the scan walk's compute rate."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from lightgbmv1_tpu.basic import Booster, _objective_string
    from lightgbmv1_tpu.io.model_text import model_to_string
    from lightgbmv1_tpu.models.predict import BatchPredictor
    from lightgbmv1_tpu.models.tree import (ensemble_predict_raw,
                                            host_trees_to_stacked)
    from tools.loadgen import run_loadgen

    trees = gb_lw.materialize_host_trees()
    ds = gb_lw.train_set
    model_str = model_to_string(
        trees, objective_string=_objective_string(gb_lw.config), num_class=1,
        num_tree_per_iteration=1, feature_names=list(ds.feature_names),
        feature_infos=ds.feature_infos())
    booster = Booster(model_str=model_str)

    work = tempfile.mkdtemp(prefix="predbench_")
    data_path = os.path.join(work, "pred_data.tsv")
    n = X.shape[0]
    # data file written once, outside every timed window (both engines and
    # the reference CLI read the same bytes)
    np.savetxt(data_path, X, fmt="%.6g", delimiter="\t")

    def file_to_file(predict_rows):
        from lightgbmv1_tpu.native import parse_dense_file

        t0 = time.time()
        Xp = parse_dense_file(data_path, False, "\t")
        if Xp is None:
            Xp = np.loadtxt(data_path, delimiter="\t")
        t_parse = time.time()
        p = predict_rows(Xp)
        t_pred = time.time()
        out_path = os.path.join(work, "pred_out.txt")
        with open(out_path, "w") as fh:
            fh.write("\n".join(f"{v:.18g}" for v in np.asarray(p).ravel()))
            fh.write("\n")
        t1 = time.time()
        return (t1 - t0, t_pred - t_parse, t_parse - t0, t1 - t_pred)

    fields = {"predict_rows": int(n), "predict_n_trees": len(trees)}

    # ---- native C++ predictor --------------------------------------------
    booster.predict(X[:256])            # warm: compile/caches outside timing
    wall, compute, parse_s, write_s = file_to_file(
        lambda Xp: booster.predict(Xp))
    fields["predict_M_rows_per_s"] = round(n / wall / 1e6, 3)
    fields["predict_native_compute_M_rows_per_s"] = round(
        n / compute / 1e6, 3)
    fields["predict_parse_ms"] = round(parse_s * 1e3, 2)
    fields["predict_write_ms"] = round(write_s * 1e3, 2)

    def median3(fn):
        ts = []
        for _ in range(3):
            t0 = time.time()
            fn()
            ts.append(time.time() - t0)
        return sorted(ts)[1]

    # ---- depth-stepped all-trees walk (the serving engine) ---------------
    bp = BatchPredictor(trees, 1, ds.num_features)
    chunk = X[: min(n, bp.chunk_rows)]
    m = chunk.shape[0]
    bucket = bp.bucket_for(m)
    codes = bp.encode(chunk)
    prebin_s = median3(lambda: bp.encode(chunk))
    padded = bp._pad(codes, bucket)
    h2d_s = median3(
        lambda: jax.device_put(padded).block_until_ready())
    codes_dev = jax.device_put(padded)
    leaf_fn = bp._leaf_fn(bucket)
    scores_fn = bp._scores_fn(bucket)

    def walk_once():
        leaf = leaf_fn(bp.arrays, codes_dev)
        jax.block_until_ready(scores_fn(bp.arrays.leaf_value, leaf))

    walk_once()                          # compile outside the window
    walk_s = median3(walk_once)
    fields["predict_prebin_ms"] = round(prebin_s * 1e3, 2)
    fields["predict_h2d_ms"] = round(h2d_s * 1e3, 2)
    fields["predict_walk_ms"] = round(walk_s * 1e3, 2)
    fields["predict_device_compute_M_rows_per_s"] = round(
        m / walk_s / 1e6, 3)
    fields["predict_h2d_bytes_per_row"] = bp.h2d_bytes(1)

    def engine_predict(Xp):
        return 1.0 / (1.0 + np.exp(-bp.predict_raw(Xp)[:, 0]))

    engine_predict(X[:256])
    wall_d, _, _, _ = file_to_file(engine_predict)
    fields["predict_device_M_rows_per_s"] = round(n / wall_d / 1e6, 3)

    # compile-amortization: repeated calls at varying batch sizes within
    # one bucket must not compile (the predictor-cache contract the
    # tests pin; recorded so a driver capture would flag a regression).
    # Read from the obs/xla.py per-label compile counters — the same
    # instrument the obs_device_ok guard and the serve smoke watch —
    # instead of the predictor's ad-hoc trace counter.
    from lightgbmv1_tpu.obs import xla as obs_xla

    bp.predict_raw(X[:1000])            # warm the 1024-row bucket
    t0_compiles = obs_xla.compile_counts()
    for nn in (1000, 777, 600, 513):    # all pad to the same bucket
        bp.predict_raw(X[:nn])
    t1_compiles = obs_xla.compile_counts()
    fields["predict_cache_retraces"] = sum(
        t1_compiles.get(k, 0) - t0_compiles.get(k, 0)
        for k in ("predict.leaf", "predict.scores", "predict.scan"))

    # ---- legacy scan walk (parity pin; the r05-era device figure) --------
    stacked = host_trees_to_stacked(trees)

    @jax.jit
    def scan_predict(xb):
        return ensemble_predict_raw(stacked, xb)

    xb_dev = jax.device_put(np.asarray(chunk, np.float32))
    jax.block_until_ready(scan_predict(xb_dev))
    scan_s = median3(lambda: jax.block_until_ready(scan_predict(xb_dev)))
    fields["predict_device_scan_M_rows_per_s"] = round(m / scan_s / 1e6, 3)

    # ---- serving megakernel (fused walk + accumulate, ISSUE 19) ----------
    # One Pallas pass per row tile walks every tree AND accumulates the
    # class scores in VMEM; plan_predict_tiles tiles the node tables when
    # they exceed the VMEM budget.  predict_fused_ok = node/bit parity
    # with the host oracle AND zero retraces within a bucket AND (on a
    # real device) >= 1.5x the scan walk's compute rate with measured
    # cost_analysis bytes confirming the single-read contract.
    bpf = BatchPredictor(trees, 1, ds.num_features, method="fused")
    fields["predict_fused_plan"] = dict(bpf.fused_plan or {})
    fields["predict_fused_engaged"] = bool(bpf._fused_engaged())
    fused_rate_ok = True
    fused_bytes_ok = True
    if bpf._fused_engaged():
        # the CPU smoke backend runs the kernel on the interpret lane
        # (exact, slow) — cap the timed window there; a real device
        # times the full chunk
        fm = m if jax.default_backend() != "cpu" else min(m, 8192)
        f_bucket = bpf.bucket_for(fm)
        codes_f_dev = jax.device_put(
            bpf._pad(bpf.encode(chunk[:fm]), f_bucket))
        ffn = bpf._fused_fn(f_bucket)
        jax.block_until_ready(ffn(bpf._fused_tables, codes_f_dev))
        fused_s = median3(lambda: jax.block_until_ready(
            ffn(bpf._fused_tables, codes_f_dev)))
        fields["predict_fused_M_rows_per_s"] = round(fm / fused_s / 1e6, 3)
        # single-read contract: the codes tile is fetched once per tile
        # sweep, the (N,T) pointer intermediate never leaves VMEM — so
        # total bytes accessed must stay near codes + tables + scores
        analytic = (f_bucket * bpf.h2d_bytes(1)
                    + sum(int(np.asarray(a).nbytes)
                          for a in bpf._fused_tables) + f_bucket * 4)
        fields["predict_fused_bytes_analytic"] = int(analytic)
        try:
            cost = (jax.jit(bpf._fused_walk())
                    .lower(bpf._fused_tables, codes_f_dev)
                    .compile().cost_analysis())
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            fields["predict_fused_bytes_accessed"] = int(
                cost.get("bytes accessed", 0))
        except Exception:
            fields["predict_fused_bytes_accessed"] = -1
        if jax.default_backend() != "cpu":
            fused_rate_ok = fused_s <= scan_s / 1.5
            measured = fields["predict_fused_bytes_accessed"]
            fused_bytes_ok = 0 < measured <= 2.0 * analytic

    # 4-bit packed serving codes: the bench model's binner needs more
    # than 16 codes per feature, so the transport figures come from a
    # packed-ELIGIBLE twin (max_bin <= 15) trained on the same rows —
    # the analytic reduction is exactly 2.0x for an even feature count.
    import lightgbmv1_tpu as lgb

    np_rows = min(n, 4096)
    yp = (np.nan_to_num(X[:np_rows, 0]) + np.nan_to_num(X[:np_rows, 1])
          > 0).astype(np.float64)
    dsp = lgb.Dataset(np.asarray(X[:np_rows], np.float64), label=yp,
                      params={"max_bin": 12, "verbosity": -1})
    bst_p = lgb.train({"objective": "binary", "max_bin": 12,
                       "num_leaves": 15, "verbosity": -1,
                       "min_data_in_leaf": 20}, dsp, num_boost_round=10)
    trees_p = bst_p._all_trees()
    bp_pk = BatchPredictor(trees_p, 1, ds.num_features, method="fused")
    bp_u8 = BatchPredictor(trees_p, 1, ds.num_features, method="fused",
                           code_layout="u8")
    fields["predict_fused_packed"] = bool(bp_pk.packed)
    fields["predict_h2d_bytes_per_row_packed"] = bp_pk.h2d_bytes(1)
    fields["predict_packed_h2d_reduction"] = round(
        bp_u8.h2d_bytes(1) / bp_pk.h2d_bytes(1), 3)
    pk_sample = np.asarray(X[:1024], np.float64)
    pk_leaf_host = np.stack(
        [t.predict_leaf_index(pk_sample) for t in trees_p], axis=1)
    packed_parity = bool(
        np.array_equal(bp_pk.predict_leaf(pk_sample), pk_leaf_host)
        and np.array_equal(bp_u8.predict_leaf(pk_sample), pk_leaf_host))
    fields["predict_packed_parity_ok"] = packed_parity
    if bp_pk._fused_engaged() and bp_pk.packed:
        pk_chunk = pk_sample
        pk_bucket = bp_pk.bucket_for(pk_chunk.shape[0])
        pk_dev = jax.device_put(bp_pk._pad(bp_pk.encode(pk_chunk),
                                           pk_bucket))
        try:
            cost = (jax.jit(bp_pk._fused_walk())
                    .lower(bp_pk._fused_tables, pk_dev)
                    .compile().cost_analysis())
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            fields["predict_packed_bytes_accessed"] = int(
                cost.get("bytes accessed", 0))
        except Exception:
            fields["predict_packed_bytes_accessed"] = -1

    # ---- regression guard -------------------------------------------------
    sample = min(n, 4096)
    leaf_dev = bp.predict_leaf(X[:sample])
    leaf_host = np.stack([t.predict_leaf_index(X[:sample]) for t in trees],
                         axis=1)
    parity_ok = bool(np.array_equal(leaf_dev, leaf_host))
    raw64 = bp.predict_raw(X[:sample], f64_exact=True)[:, 0]
    raw_host = booster.predict(X[:sample], raw_score=True,
                               predict_method="host")
    parity_ok = parity_ok and bool(np.array_equal(raw64, raw_host))
    fields["predict_parity_ok"] = parity_ok
    # the throughput leg guards the DEVICE figure (the r05-era scan walk
    # was the recorded device predictor); on the CPU smoke backend the
    # two walks are the same scalar loops and the comparison carries no
    # signal, so only parity binds there
    fields["predict_ok"] = parity_ok and (
        jax.default_backend() == "cpu"
        or fields["predict_device_compute_M_rows_per_s"]
        >= 0.95 * fields["predict_device_scan_M_rows_per_s"])

    # fused parity + compile-counter leg of predict_fused_ok: the
    # megakernel must reproduce the host oracle (leaf node-exact, f64
    # scores bit-exact) and stay retrace-free within a bucket, same as
    # the depthwise engine above
    fused_parity = bool(
        np.array_equal(bpf.predict_leaf(X[:sample]), leaf_host)
        and np.array_equal(
            bpf.predict_raw(X[:sample], f64_exact=True)[:, 0], raw_host))
    fields["predict_fused_parity_ok"] = fused_parity
    bpf.predict_raw(X[:1000])
    f0 = obs_xla.compile_counts()
    for nn in (1000, 777, 600, 513):
        bpf.predict_raw(X[:nn])
    f1 = obs_xla.compile_counts()
    fields["predict_fused_cache_retraces"] = sum(
        f1.get(k, 0) - f0.get(k, 0)
        for k in ("predict.fused", "predict.leaf", "predict.scores"))
    fields["predict_fused_ok"] = bool(
        fused_parity and packed_parity
        and fields["predict_fused_engaged"]
        and fields["predict_fused_cache_retraces"] == 0
        and fused_rate_ok and fused_bytes_ok)

    # loadgen A/B on one server: fused vs scan serving lane, same model,
    # same arrival schedule — the p99 delta a flip of predict_method
    # would buy (negative = fused faster)
    from lightgbmv1_tpu.serve import ServeConfig, Server

    p99 = {}
    pool = np.asarray(X[:4096], np.float64)
    for meth in ("fused", "scan"):
        srv = Server(booster, config=ServeConfig(
            max_batch_rows=256, max_batch_delay_ms=2.0,
            queue_depth_rows=4096,
            predictor_kwargs={"bucket_min": 64, "method": meth}))
        try:
            srv.submit(pool[:64])
            lg = run_loadgen(srv, pool, rate_qps=200.0, duration_s=2.0,
                             rows_per_req=4, n_threads=4, seed=7)
            p99[meth] = float(lg["client_p99_ms"])
        finally:
            srv.close()
    fields["serve_p99_fused_ms"] = round(p99["fused"], 3)
    fields["serve_p99_fused_delta_ms"] = round(
        p99["fused"] - p99["scan"], 3)

    if REF_PREDICT_M_ROWS_S:
        fields["predict_ref_cpp_M_rows_per_s"] = REF_PREDICT_M_ROWS_S
        fields["predict_vs_ref_same_host"] = round(
            fields["predict_M_rows_per_s"] / REF_PREDICT_M_ROWS_S, 4)
    return fields


def measure_serve(gb_lw, X):
    """Online-serving loadgen block (serve/ subsystem) — runs on EVERY
    backend including the CPU fallback (the acceptance record is a CPU
    loadgen run).  Two phases against an in-process server built from the
    bench model:

    * **live traffic + hot swap** — open-loop Poisson arrivals
      (tools/loadgen.py) at a sustainable rate with a mid-run
      ``publish()`` of a second model version; every response is checked
      BIT-IDENTICAL to ``Booster.predict`` (host path, raw scores) of the
      version tag it carries, across the swap.  ``serve_qps`` /
      ``serve_p99_ms`` / ``serve_batch_occupancy`` come from this phase.
    * **2x overload** — a deliberately small admission queue under an
      offered row rate far above capacity: the bounded queue must SHED
      (``serve_shed_frac`` > 0) while the backlog never exceeds the
      configured depth (``serve_overload_queue_ok``) — explicit rejection,
      not unbounded growth.

    ``serve_ok`` = zero failed/incorrect responses in the live phase AND
    both versions actually served across the swap AND the overload queue
    stayed bounded."""
    from lightgbmv1_tpu.basic import Booster, _objective_string
    from lightgbmv1_tpu.io.model_text import model_to_string
    from lightgbmv1_tpu.serve import ServeConfig, Server
    from tools.loadgen import run_loadgen, serve_record_fields

    trees = gb_lw.materialize_host_trees()
    ds = gb_lw.train_set
    model_str = model_to_string(
        trees, objective_string=_objective_string(gb_lw.config), num_class=1,
        num_tree_per_iteration=1, feature_names=list(ds.feature_names),
        feature_infos=ds.feature_infos())
    full = Booster(model_str=model_str)
    n_half = max(len(trees) // 2, 1)
    half = Booster(model_str=full.model_to_string(num_iteration=n_half))

    pool = np.asarray(X[:8192], np.float64)
    expected = {}   # version tag -> host raw scores over the pool

    def publish(server, booster):
        # expectation computed BEFORE the swap; check() waits out the
        # tag-assignment window (see __graft_entry__.serve_smoke)
        exp = np.asarray(booster.predict(
            pool, raw_score=True, predict_method="host"), np.float64)
        tag = server.publish(booster)
        expected[tag] = exp
        return tag

    def check(start, n, res):
        for _ in range(1000):
            if res.version in expected:
                break
            time.sleep(0.001)
        want = expected[res.version][start: start + n]
        return np.array_equal(res.values[:, 0], want)

    fields = {}
    cfg = ServeConfig(max_batch_rows=256, max_batch_delay_ms=2.0,
                      queue_depth_rows=4096, f64_scores=True,
                      predictor_kwargs={"bucket_min": 64})
    server = Server(config=cfg)
    try:
        publish(server, half)               # v1 serves the first half
        server.submit(pool[:64])            # warm the client path
        lg = run_loadgen(
            server, pool, rate_qps=float(os.environ.get(
                "SERVE_RATE_QPS", 400)), duration_s=4.0, rows_per_req=2,
            n_threads=8, seed=5, swap_at_frac=0.3,
            swap_fn=lambda: publish(server, full),
            tail_requests_after_swap=100, check_fn=check)
        fields.update(serve_record_fields(lg))
        live_ok = (lg["error"] == 0 and lg["timeout"] == 0
                   and lg["check_failures"] == 0 and lg["shed"] == 0
                   and len(lg["versions_served"]) >= 2)
        fields["serve_live_ok"] = live_ok
    finally:
        server.close()

    # ---- bounded-queue overload probe ---------------------------------
    over_cfg = ServeConfig(max_batch_rows=64, max_batch_delay_ms=1.0,
                           queue_depth_rows=256, f64_scores=True,
                           predictor_kwargs={"bucket_min": 64})
    over = Server(full, config=over_cfg)
    try:
        over.submit(pool[:64])
        lo = run_loadgen(over, pool, rate_qps=1500.0, duration_s=2.0,
                         rows_per_req=32, n_threads=16, seed=6)
        snap = lo["server_metrics"]
        fields["serve_overload_shed_frac"] = lo["shed_frac"]
        fields["serve_overload_queue_max"] = snap["queue_depth_max"]
        queue_ok = snap["queue_depth_max"] <= over_cfg.queue_depth_rows
        accounted = (lo["ok"] + lo["shed"] + lo["timeout"] + lo["error"]
                     == lo["requests"])
        fields["serve_overload_queue_ok"] = bool(queue_ok and accounted)
        fields["serve_overload_shed_observed"] = lo["shed"] > 0
    finally:
        over.close()

    fields["serve_ok"] = bool(fields.get("serve_live_ok")
                              and fields.get("serve_overload_queue_ok"))
    return fields


def measure_fleet(gb_lw, X):
    """Fault-tolerant fleet block (ISSUE 11) — on EVERY backend:

    * **replica-kill under load** — a 3-replica fleet behind the
      self-healing router takes open-loop loadgen traffic while one
      replica is killed mid-run: ``fleet_zero_error_ok`` demands ZERO
      client-visible failures (router retry/hedging absorbs the kill;
      every answer stays bit-exact to the host oracle),
      ``router_hedge_frac`` records hedge launches per completed
      request, and the dead replica must be health-check ejected.
    * **two-phase fleet publish** — a coordinated publish onto the
      degraded fleet must land one aligned version tag everywhere
      (``fleet_publish_ok``).
    * **elastic kill-resume** — an ElasticCoordinator training run
      (2-process jax.distributed where the backend supports cross-
      process CPU collectives, 1-process otherwise — recorded in
      ``fleet_elastic_world``) is killed at iteration 3 via the
      ``peer_dead`` seam and re-bootstrapped from the newest checkpoint
      bundle: ``fleet_kill_resume_ok`` pins the recovered model text
      BYTE-IDENTICAL to the uninterrupted run and ``fleet_recovery_s``
      records detection -> re-bootstrapped-and-beating wall time.

    ``fleet_ok`` = zero-error-under-kill AND ejection observed AND
    aligned publish AND byte-identical elastic resume."""
    import shutil
    import tempfile

    from lightgbmv1_tpu.basic import Booster, _objective_string
    from lightgbmv1_tpu.io.model_text import model_to_string
    from lightgbmv1_tpu.serve import Fleet, Router, RouterConfig, \
        ServeConfig
    from lightgbmv1_tpu.serve.router import hedge_frac
    from tools.loadgen import run_loadgen

    trees = gb_lw.materialize_host_trees()
    ds = gb_lw.train_set
    model_str = model_to_string(
        trees, objective_string=_objective_string(gb_lw.config),
        num_class=1, num_tree_per_iteration=1,
        feature_names=list(ds.feature_names),
        feature_infos=ds.feature_infos())
    full = Booster(model_str=model_str)
    n_half = max(len(trees) // 2, 1)
    half = Booster(model_str=full.model_to_string(num_iteration=n_half))

    pool = np.asarray(X[:4096], np.float64)
    want = np.asarray(half.predict(pool, raw_score=True,
                                   predict_method="host"), np.float64)

    def check(start, n, res):
        return np.array_equal(res.values[:, 0], want[start:start + n])

    fields = {}
    cfg = ServeConfig(max_batch_rows=128, max_batch_delay_ms=1.0,
                      queue_depth_rows=4096, f64_scores=True,
                      watchdog_ms=250.0,
                      predictor_kwargs={"bucket_min": 64})
    fleet = Fleet(half, n_replicas=3, config=cfg)
    router = Router(fleet, RouterConfig(health_period_ms=15.0,
                                        retry_max=2, hedge_ms=50.0))
    try:
        router.submit(pool[:64])
        lg = run_loadgen(
            router, pool, rate_qps=float(os.environ.get(
                "FLEET_RATE_QPS", 250)), duration_s=2.5, rows_per_req=2,
            n_threads=6, seed=7, swap_at_frac=0.4,
            swap_fn=lambda: fleet.replica("r1").close(),
            check_fn=check)
        deadline = time.time() + 3.0
        while time.time() < deadline and \
                "r1" not in router.health()["ejected_replicas"]:
            time.sleep(0.05)
        snap = router.metrics_snapshot()
        fields["fleet_requests"] = lg["requests"]
        fields["fleet_qps"] = lg["achieved_qps"]
        fields["fleet_p99_ms"] = lg["client_p99_ms"]
        fields["router_hedge_frac"] = hedge_frac(snap)
        fields["fleet_router_retries"] = snap["retries"]
        fields["fleet_zero_error_ok"] = bool(
            lg["error"] == 0 and lg["timeout"] == 0 and lg["shed"] == 0
            and lg["check_failures"] == 0 and lg["ok"] > 0)
        fields["fleet_replica_ejected_ok"] = bool(
            "r1" in router.health()["ejected_replicas"])
        try:
            tag = fleet.publish(full)
            fields["fleet_publish_ok"] = bool(fleet.version() == tag)
        except Exception as e:  # noqa: BLE001
            fields["fleet_publish_error"] = \
                f"{type(e).__name__}: {e}"[:200]
            fields["fleet_publish_ok"] = False
    finally:
        router.close()
        fleet.close()

    # ---- elastic kill-resume (parallel/elastic.py) ---------------------
    from lightgbmv1_tpu.parallel.cluster import cpu_multiprocess_supported
    from lightgbmv1_tpu.parallel.elastic import (ElasticConfig,
                                                 ElasticCoordinator)

    world = 2 if cpu_multiprocess_supported() else 1
    fields["fleet_elastic_world"] = world
    tmp = tempfile.mkdtemp(prefix="lgbm_bench_fleet_")
    try:
        rng = np.random.RandomState(0)
        Xe = rng.randn(1600, 5)
        ye = (Xe[:, 0] - Xe[:, 1] > 0).astype(float)
        data = os.path.join(tmp, "train.tsv")
        np.savetxt(data, np.column_stack([ye, Xe]), fmt="%.7g",
                   delimiter="\t")
        from lightgbmv1_tpu.config import Config as _Cfg

        ecfg = ElasticConfig.from_config(
            _Cfg.from_dict({"elastic_lease_timeout_s": 2.0,
                            "elastic_max_restarts": 1}),
            world=world, devices_per_proc=2)
        env = {k: v for k, v in os.environ.items()
               if k not in ("LGBMV1_FAULTS",)}

        def run_one(name, fault_env=None):
            wd = os.path.join(tmp, name)
            coord = ElasticCoordinator(
                wd, worker_args={
                    "data": data,
                    "model_out": os.path.join(wd, "model.txt"),
                    "iterations": 6, "snapshot_freq": 2},
                config=ecfg, fault_env=fault_env, env=env)
            res = coord.run()
            p = os.path.join(wd, "model.txt")
            return res, (open(p).read() if os.path.exists(p) else None)

        res_a, straight = run_one("straight")
        plan = [{"kind": "peer_dead", "mode": "kill",
                 "match": f"rank{world - 1}:iter3"}]
        res_b, resumed = run_one(
            "killed", fault_env={"LGBMV1_FAULTS": json.dumps(plan)})
        fields["fleet_recovery_s"] = res_b.recovery_s
        fields["fleet_restarts"] = res_b.restarts
        fields["fleet_kill_resume_ok"] = bool(
            res_a.ok and res_b.ok and straight is not None
            and straight == resumed)
    except Exception as e:  # noqa: BLE001 — partial records beat none
        fields["fleet_elastic_error"] = f"{type(e).__name__}: {e}"[:200]
        fields["fleet_kill_resume_ok"] = False
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    fields["fleet_ok"] = bool(
        fields.get("fleet_zero_error_ok")
        and fields.get("fleet_replica_ejected_ok")
        and fields.get("fleet_publish_ok")
        and fields.get("fleet_kill_resume_ok"))
    return fields


def measure_tenants(gb_lw, X):
    """Multi-tenant serving block (ISSUE 20) — on EVERY backend:

    * **compile-bucket sharing** — two tenants whose models share the
      same stacked-tree SHAPES (one is a leaf-value-scaled clone of the
      other, so thresholds/structure — and hence the shape signature —
      match while every prediction differs) publish into one server
      with shared-cache predictors: the second tenant's warm must add
      ZERO per-label XLA compiles (PR 12 counters) and mixed-tenant
      traffic must run retrace-free through ONE executable.
      ``tenant_compile_share_frac`` is the shared-jit-cache hit rate.
    * **fair-share isolation** — a hot tenant offered ~2x its fair
      share on the same server as a well-behaved cold tenant: the hot
      tenant must shed its OWN traffic (503s > 0) while the cold tenant
      keeps ZERO sheds and a p99 inside its SLO latency bound.
      ``tenant_isolation_p99_delta_ms`` = cold p99 under the overload
      minus cold p99 solo — the noisy-neighbor tax the fair-share
      admission is supposed to bound.
    * **per-tenant publish/rollback parity** — publishing v2 into
      tenant A must leave tenant B's answers bit-identical to its v1
      host oracle, and A's rollback must restore A's v1 bit-exactly.
    * **placement-move drill** — a 2-replica fleet with both tenants
      pinned to r0: overloading the hot tenant must trip the burn-rate
      signal and the placement controller must migrate it to r1 with a
      fully-attributed ``placement.move`` record.

    ``tenant_ok`` = all four probes green."""
    import copy
    import threading as _threading

    from lightgbmv1_tpu.basic import Booster, _objective_string
    from lightgbmv1_tpu.io.model_text import model_to_string
    from lightgbmv1_tpu.models import predict as predict_mod
    from lightgbmv1_tpu.obs import xla as obs_xla
    from lightgbmv1_tpu.serve import (Fleet, PlacementConfig,
                                      PlacementController, Router,
                                      RouterConfig, ServeConfig, Server,
                                      ServerOverloaded, SLOConfig,
                                      TenantRegistry)
    from tools.loadgen import run_loadgen

    trees = gb_lw.materialize_host_trees()
    ds = gb_lw.train_set

    def to_booster(tt):
        return Booster(model_str=model_to_string(
            tt, objective_string=_objective_string(gb_lw.config),
            num_class=1, num_tree_per_iteration=1,
            feature_names=list(ds.feature_names),
            feature_infos=ds.feature_infos()))

    # same structure/thresholds (same shape signature), different values
    scaled = copy.deepcopy(trees)
    for t in scaled:
        t.leaf_value = t.leaf_value * 0.5
    full, half_vals = to_booster(trees), to_booster(scaled)
    pool = np.asarray(X[:4096], np.float64)
    fields = {}

    # ---- probe 1: compile-bucket sharing ------------------------------
    predict_mod.reset_shared_cache()
    cfg = ServeConfig(max_batch_rows=128, max_batch_delay_ms=1.0,
                      queue_depth_rows=2048, f64_scores=True,
                      predictor_kwargs={"bucket_min": 64})
    server = Server(config=cfg)
    tenreg = TenantRegistry(server)
    tenreg.add("acme")
    tenreg.add("globex")
    try:
        tenreg.publish("acme", full)
        server.submit(pool[:64], tenant="acme")     # compile the bucket
        before = {k: (v["compiles"], v["retraces"])
                  for k, v in obs_xla.compile_stats().items()
                  if k.startswith("predict.")}
        tenreg.publish("globex", half_vals)         # same shapes: adopts
        ra = server.submit(pool[:64], tenant="acme")
        rg = server.submit(pool[:64], tenant="globex")
        after = {k: (v["compiles"], v["retraces"])
                 for k, v in obs_xla.compile_stats().items()
                 if k.startswith("predict.")}
        share = tenreg.compile_share_stats()
        fields["tenant_compile_share_frac"] = share["share_frac"]
        fields["tenant_shared_cache_hits"] = share["hits"]
        fields["tenant_second_warm_compiles"] = sum(
            c for c, _ in after.values()) - sum(
            c for c, _ in before.values())
        fields["tenant_mixed_retraces"] = sum(
            r for _, r in after.values()) - sum(
            r for _, r in before.values())
        values_differ = bool(np.allclose(
            np.asarray(rg.values), np.asarray(ra.values) * 0.5)
            and not np.array_equal(np.asarray(rg.values),
                                   np.asarray(ra.values)))
        fields["tenant_compile_share_ok"] = bool(
            fields["tenant_second_warm_compiles"] == 0
            and fields["tenant_mixed_retraces"] == 0
            and share["hits"] > 0 and values_differ)
    finally:
        server.close()

    # ---- probe 2: fair-share isolation under 2x hot overload ----------
    slo_ms = 250.0     # CPU-lenient latency objective for the cold SLO
    iso_cfg = ServeConfig(max_batch_rows=64, max_batch_delay_ms=1.0,
                          queue_depth_rows=512, f64_scores=True,
                          predictor_kwargs={"bucket_min": 64})
    server = Server(config=iso_cfg)
    tenreg = TenantRegistry(server)
    tenreg.add("hot")
    tenreg.add("cold", slo=SLOConfig(latency_ms=slo_ms))
    try:
        tenreg.publish("hot", full)
        tenreg.publish("cold", full)
        server.submit(pool[:64], tenant="cold")     # warm both paths
        server.submit(pool[:64], tenant="hot")

        def cold_p99(n_req=120):
            lats = []
            sheds = 0
            for i in range(n_req):
                s = (i * 17) % (pool.shape[0] - 2)
                t0 = time.monotonic()
                try:
                    server.submit(pool[s:s + 2], tenant="cold")
                    lats.append((time.monotonic() - t0) * 1e3)
                except ServerOverloaded:
                    sheds += 1
                time.sleep(0.004)
            return (float(np.percentile(lats, 99)) if lats else None,
                    sheds)

        solo_p99, _ = cold_p99()
        hot_result = {}

        def flood():
            hot_result.update(run_loadgen(
                server, pool, rate_qps=600.0, duration_s=1.6,
                rows_per_req=32, n_threads=12, seed=11,
                tenants="hot"))

        th = _threading.Thread(target=flood, daemon=True)
        th.start()
        time.sleep(0.2)                  # let the overload establish
        loaded_p99, cold_sheds = cold_p99()
        th.join()
        hot_shed = hot_result["per_tenant"]["hot"]["shed"]
        fields["tenant_cold_solo_p99_ms"] = round(solo_p99, 3)
        fields["tenant_cold_p99_ms"] = round(loaded_p99, 3)
        fields["tenant_isolation_p99_delta_ms"] = round(
            max(loaded_p99 - solo_p99, 0.0), 3)
        fields["tenant_hot_shed"] = int(hot_shed)
        fields["tenant_cold_shed"] = int(cold_sheds)
        fields["tenant_fair_share_ok"] = bool(
            hot_shed > 0 and cold_sheds == 0 and loaded_p99 <= slo_ms)
    finally:
        server.close()

    # ---- probe 3: per-tenant publish/rollback parity ------------------
    server = Server(config=cfg)
    tenreg = TenantRegistry(server)
    tenreg.add("a")
    tenreg.add("b")
    try:
        want_full = np.asarray(full.predict(
            pool[:256], raw_score=True, predict_method="host"),
            np.float64)
        want_half = np.asarray(half_vals.predict(
            pool[:256], raw_score=True, predict_method="host"),
            np.float64)
        tenreg.publish("a", half_vals)
        tenreg.publish("b", half_vals)
        tenreg.publish("a", full)       # v2 into A only
        got_a = server.submit(pool[:256], tenant="a").values[:, 0]
        got_b = server.submit(pool[:256], tenant="b").values[:, 0]
        a_v2_ok = np.array_equal(got_a, want_full)
        b_iso_ok = np.array_equal(got_b, want_half)
        tenreg.rollback("a")
        got_a1 = server.submit(pool[:256], tenant="a").values[:, 0]
        fields["tenant_publish_parity_ok"] = bool(
            a_v2_ok and b_iso_ok
            and np.array_equal(got_a1, want_half)
            and tenreg.version("a") == "v1"
            and tenreg.version("b") == "v1")
    finally:
        server.close()

    # ---- probe 4: placement-move drill --------------------------------
    move_cfg = ServeConfig(max_batch_rows=64, max_batch_delay_ms=1.0,
                           queue_depth_rows=256, f64_scores=True,
                           predictor_kwargs={"bucket_min": 64})
    fleet = Fleet(n_replicas=2, config=move_cfg)
    router = Router(fleet, RouterConfig(health_period_ms=50.0,
                                        retry_max=0))
    tenreg = TenantRegistry(fleet)
    tenreg.add("hot")
    tenreg.add("quiet")
    try:
        tenreg.publish("hot", full)
        tenreg.publish("quiet", full)
        router.set_placement("hot", ["r0"])
        router.set_placement("quiet", ["r0"])
        pc = PlacementController(fleet, router, PlacementConfig(
            replicas_per_tenant=1, burn_threshold=2.0,
            occupancy_frac=0.75, cooldown_s=0.0))
        # burn error budget on r0's hot tenant: a request over the
        # fair-share row cap sheds deterministically, each shed is an
        # SLO failure, and the fast-window burn rate trips the mover
        n_over = move_cfg.queue_depth_rows    # > any tenant's share
        for _ in range(20):
            try:
                router.submit(pool[:n_over], tenant="hot")
            except ServerOverloaded:
                pass
        moves = pc.step()
        fields["tenant_placement_moves"] = len(moves)
        mv = moves[0] if moves else {}
        fields["tenant_placement_move_ok"] = bool(
            moves and mv.get("tenant") == "hot"
            and mv.get("from") == "r0"
            and mv.get("to") == "r1"
            and router.placement().get("hot") == ("r1",)
            and router.placement().get("quiet") == ("r0",)
            and mv.get("burn_rate") is not None
            and "warm_compile_ms" in mv)
    finally:
        router.close()
        fleet.close()

    fields["tenant_ok"] = bool(
        fields.get("tenant_compile_share_ok")
        and fields.get("tenant_fair_share_ok")
        and fields.get("tenant_publish_parity_ok")
        and fields.get("tenant_placement_move_ok"))
    return fields


def measure_chaos():
    """Robustness block (PR 6): the scripted fault suite (tools/chaos.py)
    runs its fast deterministic subset on EVERY backend — kill-and-resume
    (bit-identical model text), torn-snapshot fallback, poisoned
    gradients (finite_guard detect + clamp), publish-of-garbage (the
    corrupt model never serves), dispatcher stall/death (watchdog),
    bounded-queue overload, and transient-H2D retry.  ``chaos_ok`` is
    the guard: EVERY injected fault must be recovered."""
    from tools.chaos import run_suite

    rec = run_suite(fast=True)
    return {
        "chaos_ok": bool(rec["chaos_ok"]),
        "chaos_n_scenarios": rec["n_scenarios"],
        "chaos_scenarios": {k: bool(v.get("ok"))
                            for k, v in rec["scenarios"].items()},
        # flight-recorder contract (ISSUE 10): kill/wedge scenarios left
        # exactly one validated bundle each, recovered faults left none
        "chaos_forensics_ok": bool(rec.get("forensics_ok")),
        # the fault-tolerant-fleet scenario subset (ISSUE 11)
        "chaos_fleet_ok": bool(rec.get("chaos_fleet_ok")),
        "chaos_seconds": round(sum(v.get("seconds", 0)
                                   for v in rec["scenarios"].values()), 1),
    }


def measure_stream(X, y, backend: str):
    """Out-of-core streaming block (PR 8, data/ subsystem): write the
    sharded block cache once, train from it with the row-block streaming
    trainer, and compare against the resident trainer at the SAME
    sequential schedule.

    ``stream_ok`` is the acceptance guard: byte-identical model text
    (the parity contract) AND ledger-accounted peak device bytes within
    the analytic O(stream_block_rows · F) bound — i.e. bounded by block
    size and leaf-sized state, never by dataset rows."""
    import tempfile
    import time as _time

    import lightgbmv1_tpu as lgb

    n = min(len(y), 20_000 if backend == "cpu" else 200_000)
    Xs, ys = X[:n], y[:n]
    F = Xs.shape[1]
    iters = 3
    block_rows = 4096
    params = {
        "objective": "binary", "num_leaves": 31, "max_bin": 63,
        "learning_rate": 0.1, "min_data_in_leaf": 20, "verbosity": -1,
        "tree_growth": "leafwise_masked", "seed": 7,
        "bagging_fraction": 0.8, "bagging_freq": 2,
        "feature_fraction": 0.9,
    }
    fields = {"stream_block_rows": block_rows, "stream_rows": n}

    ds = lgb.Dataset(Xs, label=ys, params=dict(params))
    ds.construct()
    t0 = _time.perf_counter()
    b_res = lgb.train(dict(params), ds, num_boost_round=iters,
                      verbose_eval=False)
    res_dt = (_time.perf_counter() - t0) / iters
    text_res = b_res.model_to_string()
    matrix_bytes = int(ds._binned.binned.nbytes)

    with tempfile.TemporaryDirectory() as td:
        cache = os.path.join(td, "blocks")
        ds.save_block_cache(cache, block_rows=block_rows)
        sds = lgb.Dataset(cache, params=dict(params))
        t0 = _time.perf_counter()
        b_str = lgb.train(dict(params), sds, num_boost_round=iters,
                          verbose_eval=False)
        str_dt = (_time.perf_counter() - t0) / iters
        text_str = b_str.model_to_string()
        peak = int(b_str._gbdt.stream_peak_device_bytes)
        peak_tags = dict(b_str._gbdt._ledger.peak_tags)

    parity_ok = text_res == text_str
    # analytic device bound: leaf-sized state (pool + accumulators) +
    # double-buffered blocks (bins + g3 + lid per block, 2 in flight) +
    # one transient (N,)-draw per bagging period + slack for small state
    B = 64
    L = params["num_leaves"]
    block_bytes = block_rows * (F + 12 + 4)
    bound = (L + 3) * F * B * 3 * 4 + 4 * block_bytes + 8 * n + (1 << 20)
    mem_ok = peak <= bound
    fields.update({
        "stream_ms_per_iter": round(str_dt * 1e3, 2),
        "stream_resident_ms_per_iter": round(res_dt * 1e3, 2),
        "stream_vs_resident_ratio": round(str_dt / max(res_dt, 1e-9), 3),
        "stream_peak_device_bytes": peak,
        "stream_peak_device_bound_bytes": int(bound),
        "stream_resident_matrix_bytes": matrix_bytes,
        "stream_peak_tags": {k: int(v) for k, v in peak_tags.items()},
        "stream_parity_ok": bool(parity_ok),
        "stream_mem_ok": bool(mem_ok),
        "stream_ok": bool(parity_ok and mem_ok),
    })
    return fields


def obs_overhead_guard_ok(frac, abs_ms, rel_bar=0.02, abs_floor_ms=20.0):
    """The obs tracer A/B guard with the drift-block treatment (ISSUE 15
    satellite): armed overhead passes at <= 2% RELATIVE **or** <= 20 ms
    ABSOLUTE.  The PR 14 session measured 0.0201 vs the bare 0.02 bar in
    one of three otherwise-identical CPU runs — at a ~1 s off-wall that
    relative sliver is ~20 ms of scheduler noise, far below anything the
    tracer itself could cost; the absolute floor keeps the guard
    meaningful on fast walls without letting a real regression hide on
    slow ones.  Pure so tests can pin the formula
    (tests/test_obs.py)."""
    if not isinstance(frac, (int, float)):
        return False
    if frac <= rel_bar:
        return True
    return isinstance(abs_ms, (int, float)) and abs_ms <= abs_floor_ms


def measure_obs(X, y, backend: str, phase_fields=None):
    """Observability self-measurement (ISSUE 9): the obs/ layer's cost
    and validity, recorded like any other device-sensitive claim.

    * **A/B overhead** — the same per-iteration training run with the
      span tracer OFF (the default) and ARMED; ``obs_overhead_frac`` is
      the armed wall over the off wall (min-of-3 each, alternated), and
      the off-path contract is bit-parity: both runs' model text must be
      byte-identical (``obs_parity_ok`` — tracing may never perturb
      training).
    * **train trace validity** — the armed run's Chrome export must be
      valid trace-event JSON whose ``train.iteration`` spans sum to the
      measured train wall within 10% (``obs_span_cover_frac`` /
      ``obs_trace_ok``).  When the capture carries phase fields, the
      measured ``phase_attrib`` breakdown is installed as the tracer's
      phase profile first, so the estimated phase child spans in the
      trace agree with the record's attribution by construction.
    * **serve trace + exposition** — a short traced loadgen window: every
      completed request must appear as ``serve.queue``/``serve.walk``
      span pairs carrying its trace id (``obs_serve_trace_ok``), and the
      server's ``prometheus_text()`` must parse with monotone histogram
      buckets (``obs_prom_ok``).
    * **SLO burn-rate** (ISSUE 10) — the loadgen window's always-on
      tracker must report a sane evaluation (SLIs in [0,1], finite burn
      rates, worst-tail exemplar trace ids on the latency buckets) and
      the multi-window alert logic must page on synthetic budget-burning
      traffic and stay quiet on clean traffic (``slo_ok``).
    * **forensics drill** (ISSUE 10) — an armed flight recorder must
      write exactly ONE validated bundle per arming (``forensics_ok``);
      the chaos suite separately asserts the real kill/wedge paths
      (``chaos_forensics_ok``).
    * **aggregation probe** (ISSUE 10) — the loadgen + server artifacts
      of the window must merge into one Chrome trace with distinct pid
      lanes and one additive metrics snapshot (``obs_agg_ok``).
    * **device truth** (ISSUE 12) — the compile/memory telemetry of
      obs/xla.py, read back as record fields: ``compile_ms_total`` and
      per-label ``compile_counts``/``retrace_counts`` of every
      instrumented dispatch this bench process compiled; a serving
      bucket probe whose per-label compile counters must NOT move across
      varied batch sizes inside one bucket (``serve_bucket_retraces`` —
      the zero-retrace contract asserted via the new counters instead of
      the predictor's ad-hoc trace counter); ``hbm_peak_bytes`` from
      ``device.memory_stats()`` (None on CPU — graceful absence)
      reconciled against the streaming ``DeviceLedger`` gauge
      (``ledger_agreement``); and, when the capture carries phase fields
      and a matmul peak, the per-phase roofline join
      (``phase_roofline`` — tools/phase_attrib.roofline_attribution over
      the cost-analysis split).  Guard ``obs_device_ok``.

    ``obs_ok`` = overhead <= 2% AND parity AND both traces valid AND the
    exposition healthy AND slo/forensics/aggregation green AND the
    device-truth block green — the events ring, SLO tracker and compile
    telemetry are always-on, so their cost sits inside the measured A/B
    walls."""
    import shutil
    import tempfile

    import lightgbmv1_tpu as lgb
    from lightgbmv1_tpu.obs import agg as obs_agg
    from lightgbmv1_tpu.obs import dump as obs_dump
    from lightgbmv1_tpu.obs import events as obs_events
    from lightgbmv1_tpu.obs import trace
    from lightgbmv1_tpu.serve import ServeConfig, Server
    from lightgbmv1_tpu.serve.slo import SLOConfig, SLOTracker
    from tools.loadgen import run_loadgen

    n = min(len(y), 20_000 if backend == "cpu" else 100_000)
    Xs, ys = X[:n], y[:n]
    iters = 8
    params = {
        "objective": "binary", "num_leaves": 31, "max_bin": 63,
        "learning_rate": 0.1, "min_data_in_leaf": 20, "verbosity": -1,
        "tree_growth": "leafwise", "seed": 11,
    }
    fields = {}
    trace.reset()
    # bin ONCE outside the timed window: the A/B judges the tracer's
    # per-iteration cost, and dataset construction is pure shared noise
    ds_ab = lgb.Dataset(Xs, label=ys, params=dict(params))
    ds_ab.construct()

    def train_once(armed):
        if armed:
            trace.arm(ring_events=1 << 16)
            if phase_fields:
                from tools.phase_attrib import phase_ms_from_fields

                # the canonical phase list (tools/phase_attrib.py): a
                # fused capture's merged hist+split row rides along
                trace.set_phase_profile(
                    phase_ms_from_fields(phase_fields),
                    phase_fields.get("wave_rounds_per_tree"))
        else:
            trace.disarm()
        t0 = time.perf_counter()
        bst = lgb.train(dict(params), ds_ab, num_boost_round=iters,
                        verbose_eval=False)
        dt = time.perf_counter() - t0
        return dt, bst.model_to_string()

    try:
        # alternate off/armed, min-of-repeated-medians (the drift
        # block's A/B discipline, ISSUE 15 satellite): run-to-run noise
        # on a busy host dwarfs the nanoseconds a span record costs —
        # the inner median damps per-run hiccups, the outer min damps
        # sustained interference; the bare min-of-3 flickered 0.0201 vs
        # the 0.02 bar in one of three otherwise-identical PR 14 runs
        off_meds, armed_meds = [], []
        off_text = armed_text = None
        trace_doc = None
        armed_wall = None
        for _ in range(2):                      # outer reps -> min
            offs, arms = [], []
            for _ in range(3):                  # inner reps -> median
                dt, off_text = train_once(armed=False)
                offs.append(dt)
                dt, armed_text = train_once(armed=True)
                arms.append(dt)
                if armed_wall is None or dt <= armed_wall:
                    armed_wall = dt
                    trace_doc = trace.export_chrome()
            off_meds.append(float(np.median(offs)))
            armed_meds.append(float(np.median(arms)))
        off_dt, armed_dt = min(off_meds), min(armed_meds)
        overhead = max((armed_dt - off_dt) / max(off_dt, 1e-9), 0.0)
        fields["obs_overhead_frac"] = round(overhead, 4)
        fields["obs_overhead_abs_ms"] = round(
            max((armed_dt - off_dt) * 1e3, 0.0), 3)
        fields["obs_parity_ok"] = bool(off_text == armed_text)

        evs = [e for e in trace_doc["traceEvents"] if e.get("ph") == "X"]
        iter_spans = [e for e in evs if e.get("name") == "train.iteration"]
        span_sum_s = sum(e["dur"] for e in iter_spans) / 1e6
        cover = span_sum_s / max(armed_wall, 1e-9)
        fields["obs_trace_events"] = len(evs)
        fields["obs_span_cover_frac"] = round(cover, 4)
        # iteration spans must exist, nest sanely and cover the train
        # wall within 10% (dataset construction is outside the spans, so
        # cover is measured against the post-construction train leg —
        # approximated by the span sum bound 0.5..1.02 of total wall)
        fields["obs_trace_ok"] = bool(
            len(iter_spans) == iters
            and all(e["dur"] >= 0 and e["ts"] >= 0 for e in evs)
            and 0.0 < cover <= 1.10)
    finally:
        trace.reset()

    # ---- serve: traced loadgen window + Prometheus exposition ----------
    ds_full = lgb.Dataset(Xs, label=ys, params=dict(params))
    bst = lgb.train(dict(params), ds_full, num_boost_round=iters,
                    verbose_eval=False)
    pool = np.asarray(Xs[:2048], np.float64)
    cfg = ServeConfig(max_batch_rows=128, max_batch_delay_ms=2.0,
                      queue_depth_rows=2048, f64_scores=True,
                      predictor_kwargs={"bucket_min": 64})
    server = Server(bst, config=cfg)
    art_dir = tempfile.mkdtemp(prefix="bench_obs_agg_")
    try:
        server.submit(pool[:32])            # warm the compiled path
        trace.arm(ring_events=1 << 15)
        lg = run_loadgen(server, pool, rate_qps=150.0, duration_s=1.5,
                         rows_per_req=2, n_threads=4, seed=9,
                         export_artifacts_to=art_dir)
        serve_doc = trace.export_chrome()
        # server-side artifact (same span ring + the replica registry)
        # while the ring still holds the window — the aggregation probe
        # below merges it with the loadgen's client artifact
        ident = obs_events.identity()
        obs_agg.export_process_artifacts(
            art_dir, label=f"server-{ident['host']}-{ident['pid']}",
            registry=server.metrics.registry)
        trace.reset()
        sev = serve_doc["traceEvents"]
        q_ids = {e["args"]["trace_id"] for e in sev
                 if e.get("name") == "serve.queue"}
        w_ids = {e["args"]["trace_id"] for e in sev
                 if e.get("name") == "serve.walk"}
        batches = [e for e in sev if e.get("name") == "serve.batch"]
        fields["obs_serve_trace_events"] = len(sev)
        fields["obs_serve_trace_ok"] = bool(
            lg["ok"] > 0 and batches
            and len(q_ids) >= lg["ok"] and q_ids == w_ids)
        prom = server.metrics.prometheus_text()
        mono_ok = True
        last_name, last_v = None, -1
        for line in prom.splitlines():
            if "_bucket{" in line and not line.startswith("#"):
                name = line.split("{", 1)[0]
                v = float(line.rsplit(" ", 1)[1])
                if name == last_name and v < last_v:
                    mono_ok = False
                last_name, last_v = name, v
            else:
                last_name, last_v = None, -1
        om_text = server.metrics.prometheus_text(exemplars=True)
        fields["obs_prom_ok"] = bool(
            "# TYPE serve_latency_ms histogram" in prom
            and "serve_completed_total" in prom and mono_ok
            # exemplars render ONLY under OpenMetrics negotiation: the
            # 0.0.4 exposition stays grammar-clean for classic scrapers
            and " # {trace_id=" not in prom
            and " # {trace_id=" in om_text)

        # ---- SLO: live-window evaluation + deterministic alert probe --
        slo = server.slo_snapshot()
        fast_a = slo["availability"]["windows"]["fast"]
        fast_l = slo["latency"]["windows"]["fast"]
        exemplars = slo.get("exemplars", [])
        fields["slo_availability"] = fast_a["sli"]
        fields["slo_latency_sli"] = fast_l["sli"]
        fields["slo_availability_burn"] = fast_a["burn_rate"]
        fields["slo_exemplars"] = len(exemplars)
        sane = (0.0 <= fast_a["sli"] <= 1.0
                and 0.0 <= fast_l["sli"] <= 1.0
                and fast_a["burn_rate"] >= 0.0
                and slo["lifetime"]["total"] >= lg["ok"]
                and exemplars
                and all(len(str(e.get("trace_id", ""))) == 16
                        for e in exemplars)
                and json.dumps(slo))   # GET /slo payload serializes
        # alert logic, replayed deterministically: 50% failures must
        # page both windows; clean traffic must not
        burn_cfg = SLOConfig(fast_window_s=60.0, slow_window_s=600.0)
        hot, cold = SLOTracker(burn_cfg), SLOTracker(burn_cfg)
        for i in range(400):
            hot.record(i % 2 == 0, latency_ms=1.0, trace_id="x" * 16,
                       now=1_000.0 + i * 0.1)
            cold.record(True, latency_ms=1.0, trace_id="y" * 16,
                        now=1_000.0 + i * 0.1)
        alerts_ok = (
            hot.evaluate(now=1_040.0)["alerts"]["availability_page"]
            and not cold.evaluate(
                now=1_040.0)["alerts"]["availability_page"])
        fields["slo_ok"] = bool(sane and alerts_ok)

        # ---- aggregation probe: loadgen + server -> one timeline ------
        agg_summary = obs_agg.aggregate_dir(art_dir)
        with open(agg_summary["merged_metrics"]) as fh:
            merged = json.load(fh)["merged"]
        fields["obs_agg_sources"] = len(agg_summary["sources"])
        fields["obs_agg_ok"] = bool(
            agg_summary["lanes"] >= 2
            and merged.get('loadgen_requests_total{outcome="ok"}')
            == lg["ok"]
            and merged.get("serve_completed_total", 0) >= lg["ok"])
    finally:
        trace.reset()
        server.close()
        shutil.rmtree(art_dir, ignore_errors=True)

    # ---- forensics drill: one validated bundle per arming --------------
    fdir = tempfile.mkdtemp(prefix="bench_forensics_")
    try:
        with obs_dump.armed_dir(fdir, config={"bench_drill": True}):
            first = obs_dump.dump("bench_drill", error="forensics drill")
            second = obs_dump.dump("bench_drill")   # latched: must no-op
        bundles = obs_dump.list_bundles(fdir)
        manifest = (obs_dump.validate_bundle(bundles[0])
                    if len(bundles) == 1 else None)
        fields["forensics_ok"] = bool(
            first and second is None and len(bundles) == 1
            and manifest and manifest["reason"] == "bench_drill"
            and manifest["identity"]["pid"] == os.getpid())
    except Exception:   # noqa: BLE001 — a broken recorder FAILS the guard
        fields["forensics_ok"] = False
    finally:
        shutil.rmtree(fdir, ignore_errors=True)

    # ---- device truth (ISSUE 12): compile/memory/cost telemetry --------
    try:
        from lightgbmv1_tpu.models.predict import BatchPredictor
        from lightgbmv1_tpu.obs import xla as obs_xla
        from lightgbmv1_tpu.obs.metrics import default_registry

        # serving bucket path: warm one bucket, then varied batch sizes
        # INSIDE it — the per-label compile counters must not move (the
        # compile-amortization contract, now watched by the obs/xla.py
        # counters every instrumented dispatch shares)
        trees = bst._gbdt.materialize_host_trees()
        bp = BatchPredictor(trees, 1, Xs.shape[1], bucket_min=64)
        bp.predict_raw(pool[:200])          # warm the 256-row bucket
        before = obs_xla.compile_counts()
        for nn in (200, 180, 150, 129):
            bp.predict_raw(pool[:nn])
        after = obs_xla.compile_counts()
        serve_retraces = sum(
            after.get(k, 0) - before.get(k, 0)
            for k in ("predict.leaf", "predict.scores", "predict.scan"))
        fields["serve_bucket_retraces"] = int(serve_retraces)

        # process-cumulative compile telemetry: every labeled dispatch
        # this bench compiled (train step/scan, growers, predict walks)
        stats = obs_xla.compile_stats()
        fields["compile_ms_total"] = round(obs_xla.compile_ms_total(), 1)
        fields["compile_counts"] = obs_xla.compile_counts()
        fields["retrace_counts"] = obs_xla.retrace_counts()
        fallbacks = {k: v["fallbacks"] for k, v in stats.items()
                     if v.get("fallbacks")}
        if fallbacks:
            fields["xla_instrument_fallbacks"] = fallbacks
        step = stats.get("train.scan") or stats.get("train.step") or {}
        fields["train_step_flops"] = step.get("flops")
        fields["train_step_bytes_accessed"] = step.get("bytes_accessed")
        fields["train_step_temp_bytes"] = step.get("temp_bytes")

        # live device memory vs the streaming ledger's analytic bound
        mem = obs_xla.sample_device_memory()
        fields["hbm_peak_bytes"] = (
            int(mem["peak_bytes_in_use"])
            if mem and "peak_bytes_in_use" in mem else None)
        gauge = default_registry().get("stream_peak_device_bytes")
        ledger_peak = gauge.get() if gauge is not None else None
        fields["ledger_agreement"] = obs_xla.ledger_agreement(
            ledger_peak, fields["hbm_peak_bytes"])

        # roofline join: measured phase ms x cost-analysis flops/bytes
        # against the same-session matmul peak (device captures only —
        # the CPU smoke has neither phase fields nor a peak)
        if phase_fields and (
                phase_fields.get("phase_hist_ms") is not None
                or phase_fields.get("phase_round_fused_ms") is not None
                or phase_fields.get("phase_hist_split_fused_ms")
                is not None) \
                and phase_fields.get("device_matmul_peak_tf_s"):
            from tools.phase_attrib import (phase_ms_from_fields,
                                            roofline_attribution,
                                            split_cost_by_ms)

            # canonical phase list (tools/phase_attrib.py): a fused
            # capture's single merged hist+split phase gets its own
            # labeled roofline row instead of pooling into phase_other
            pms = phase_ms_from_fields(phase_fields)
            pms.pop("valid_route", None)   # valid routing is not part of
                                           # the compiled train step's
                                           # cost analysis split
            cost = split_cost_by_ms(step.get("flops"),
                                    step.get("bytes_accessed"), pms)
            rl = roofline_attribution(
                pms, cost,
                phase_fields["device_matmul_peak_tf_s"] * 1e12)
            if rl:
                fields["phase_roofline"] = rl

        train_labels = [k for k in fields["compile_counts"]
                        if k.startswith(("train.", "grow."))]
        fields["obs_device_ok"] = bool(
            fields["compile_ms_total"] > 0
            and train_labels
            and serve_retraces == 0
            and not fallbacks
            and (fields["hbm_peak_bytes"] is None
                 or fields["hbm_peak_bytes"] > 0)
            and (fields["ledger_agreement"] is None
                 or 0 < fields["ledger_agreement"] <= 1.5))
    except Exception as e:   # noqa: BLE001 — a broken instrument FAILS
        fields["obs_device_error"] = f"{type(e).__name__}: {e}"[:200]
        fields["obs_device_ok"] = False

    fields["obs_ok"] = bool(
        obs_overhead_guard_ok(fields.get("obs_overhead_frac"),
                              fields.get("obs_overhead_abs_ms"))
        and fields.get("obs_parity_ok")
        and fields.get("obs_trace_ok")
        and fields.get("obs_serve_trace_ok")
        and fields.get("obs_prom_ok")
        and fields.get("slo_ok")
        and fields.get("forensics_ok")
        and fields.get("obs_agg_ok")
        and fields.get("obs_device_ok"))
    return fields


def measure_drift(X, y, backend: str):
    """Model-quality & data-drift block (ISSUE 14): the skew-injection
    probe, the quality telemetry summary, and the reference parity +
    overhead contracts — on every backend.

    * **skew-injection probe** — a drift-armed Server (bounded sampling
      ring, obs/drift.py) under two deterministic traffic phases: CLEAN
      rows drawn from the training distribution must raise ZERO false
      alarms (``drift_clean_ok``: no feature over the PSI threshold, no
      score alert), then the same rows with one feature shifted +3
      sigma must be DETECTED (``drift_detect_ok``: the injected feature
      alerts, ranks top-1, and publishes a ``drift.alert`` event).
    * **reference parity** — the serialized training reference of the
      streaming trainer must be BYTE-IDENTICAL to the resident
      trainer's at the parity schedule (``drift_ref_stream_parity_ok``).
    * **armed overhead** — serving the same batches with sampling armed
      vs off (min-of-3 alternated, the measure_obs methodology):
      ``drift_overhead_frac`` must stay within the PR 9 <= 2% contract
      (``drift_overhead_ok``).
    * **quality telemetry** — obs/model.quality_snapshot of the probe
      model: split-gain distribution, leaf/depth means, top gain
      features and the final valid metric, published into the metrics
      registry (publish_quality) and recorded as train_* fields for
      perf_report's "Model quality" section.

    ``drift_ok`` = clean AND detect AND reference parity AND overhead —
    required by ``ci_gate --require-guards default``.
    """
    import tempfile

    import lightgbmv1_tpu as lgb
    from lightgbmv1_tpu.obs.model import publish_quality
    from lightgbmv1_tpu.serve import Server
    from lightgbmv1_tpu.serve.server import ServeConfig

    n = min(len(y), 20_000 if backend == "cpu" else 100_000)
    Xs, ys = np.asarray(X[:n], np.float64), y[:n]
    params = {
        "objective": "binary", "num_leaves": 31, "max_bin": 63,
        "learning_rate": 0.1, "min_data_in_leaf": 20, "verbosity": -1,
        "tree_growth": "leafwise", "seed": 13, "metric": "auc",
    }
    fields = {}

    # -- probe model + quality telemetry ---------------------------------
    ds = lgb.Dataset(Xs, label=ys, params=dict(params))
    evals = {}
    bst = lgb.train(dict(params), ds, num_boost_round=5,
                    valid_sets=[ds], valid_names=["train"],
                    evals_result=evals, verbose_eval=False)
    ref = bst.capture_model_reference()
    qs = bst.quality_snapshot()
    publish_quality(qs)
    fields.update({
        "train_split_gain_p50": qs["split_gain"].get("p50"),
        "train_split_gain_p90": qs["split_gain"].get("p90"),
        "train_tree_leaves_mean": qs["tree_leaves"].get("mean"),
        "train_tree_depth_mean": qs["tree_depth"].get("mean"),
        "train_top_gain_features": [d["feature"]
                                    for d in qs["importance_top"][:5]],
        "train_metric_final": {k: round(v[-1], 6)
                               for k, v in qs["metric_history"].items()},
    })

    # -- streamed-vs-resident reference byte parity ----------------------
    ns = min(n, 8000)
    sp = {**params, "tree_growth": "leafwise_masked", "metric": []}
    ds_s = lgb.Dataset(Xs[:ns].copy(), label=ys[:ns], params=dict(sp))
    ds_s.construct()
    b_res = lgb.train(dict(sp), ds_s, num_boost_round=2,
                      verbose_eval=False)
    with tempfile.TemporaryDirectory() as td:
        cache = os.path.join(td, "blocks")
        ds_s.save_block_cache(cache, block_rows=2048)
        b_str = lgb.train(dict(sp), lgb.Dataset(cache, params=dict(sp)),
                          num_boost_round=2, verbose_eval=False)
        ref_parity = (b_res.capture_model_reference().to_bytes()
                      == b_str.capture_model_reference().to_bytes())
    fields["drift_ref_stream_parity_ok"] = bool(ref_parity)

    # -- skew-injection probe on a drift-armed server --------------------
    from lightgbmv1_tpu.obs import events as obs_events

    scfg = dict(max_batch_delay_ms=0.5, drift_sample_rows=4096,
                drift_min_rows=512, drift_per_batch_rows=128)
    rows_per, n_batches = 256, 16
    clean = Xs[: rows_per * n_batches]
    skew_feature = 0
    skewed = clean.copy()
    skewed[:, skew_feature] += 3.0 * clean[:, skew_feature].std()
    srv = Server(config=ServeConfig(**scfg))
    try:
        srv.publish(bst, model_reference=ref)
        for i in range(n_batches):
            srv.submit(clean[i * rows_per:(i + 1) * rows_per])
        snap_clean = srv.drift_snapshot()
        clean_alarms = (len(snap_clean.get("alerting", []))
                        + int(bool(snap_clean.get("score_alerting"))))
        clean_ok = bool(snap_clean.get("evaluated")) and clean_alarms == 0
        for i in range(n_batches):
            srv.submit(skewed[i * rows_per:(i + 1) * rows_per])
        snap_skew = srv.drift_snapshot()
        want = f"Column_{skew_feature}"
        top = snap_skew.get("top") or [{}]
        detect_ok = (want in snap_skew.get("alerting", [])
                     and top[0].get("feature") == want)
        alert_events = len([e for e in obs_events.tail(1024)
                            if e.get("kind") == "drift.alert"
                            and e.get("fields", {}).get("version")
                            == srv.version()])
        fields.update({
            "drift_sample_rows": scfg["drift_sample_rows"],
            "drift_rows_sampled": snap_skew.get(
                "ring", {}).get("rows_sampled"),
            "drift_clean_psi_max": snap_clean.get("psi_max"),
            "drift_clean_false_alarms": int(clean_alarms),
            "drift_clean_ok": bool(clean_ok),
            "drift_injected_psi": (None if top[0].get("feature") != want
                                   else top[0].get("psi")),
            "drift_score_psi_injected": snap_skew.get("score_psi"),
            "drift_alert_events": int(alert_events),
            "drift_detect_ok": bool(detect_ok and alert_events >= 1),
        })
    finally:
        srv.close()

    # -- armed-overhead A/B (the PR 9 <= 2% serving contract) ------------
    # ONE persistent server, the sampling knob toggled between phases
    # (the dispatcher reads it per batch): same threads, same compiled
    # executables, same queue state for both sides.  The instrument is
    # the MEDIAN per-batch submit latency, not a wall total — the
    # sampling cost is ~10 us per batch (one strided slice copy) while
    # a single scheduler hiccup on a 1-core box costs milliseconds, so
    # a wall-total A/B at this window size reads hiccups as "overhead";
    # medians put the hiccups in the tail where they belong.
    # Alternated x4 so drift in machine load hits both sides equally.
    ob_batches = n_batches * 4

    def batch_lat_ms(s):
        out = []
        for i in range(ob_batches):
            j = (i % n_batches) * rows_per
            t0 = time.perf_counter()
            s.submit(clean[j: j + rows_per])
            out.append((time.perf_counter() - t0) * 1e3)
        return out

    s_ab = Server(config=ServeConfig(**scfg))
    try:
        s_ab.publish(bst, model_reference=ref)
        s_ab.submit(clean[:rows_per])           # warm bucket + detector
        med_off = med_arm = 1e30
        for _ in range(5):
            # min-of-rep-medians: the median damps per-batch hiccups
            # within a rep, the min damps rep-scale load drift — the
            # same two-level damping the other A/B blocks use
            s_ab.config.drift_sample_rows = 0
            med_off = min(med_off, float(np.median(batch_lat_ms(s_ab))))
            s_ab.config.drift_sample_rows = scfg["drift_sample_rows"]
            med_arm = min(med_arm, float(np.median(batch_lat_ms(s_ab))))
    finally:
        s_ab.close()
    overhead = med_arm / max(med_off, 1e-9) - 1.0
    fields["drift_batch_p50_ms_off"] = round(med_off, 4)
    fields["drift_batch_p50_ms_armed"] = round(med_arm, 4)
    fields["drift_overhead_frac"] = round(max(overhead, 0.0), 4)
    # the contract is relative (<= 2%) with an absolute floor: on the
    # CPU smoke's ~1.6 ms batches 2% is ~32 us — the scheduler/clock
    # noise floor of a threaded submit path — while the actual armed
    # cost is one strided row copy every sample_stride batches
    # (~10 us amortized).  A delta under 50 us/batch satisfies the
    # contract at ANY realistic batch wall; device captures (ms-scale
    # walks) are judged by the relative bar alone.
    fields["drift_overhead_ok"] = bool(overhead <= 0.02
                                       or (med_arm - med_off) <= 0.05)
    fields["drift_ok"] = bool(
        fields["drift_clean_ok"] and fields["drift_detect_ok"]
        and fields["drift_ref_stream_parity_ok"]
        and fields["drift_overhead_ok"])
    return fields


def main():
    import jax

    from lightgbmv1_tpu.config import Config
    from lightgbmv1_tpu.io.dataset import BinnedDataset
    from lightgbmv1_tpu.models.gbdt import create_boosting

    backend = jax.default_backend()
    N = int(os.environ.get("BENCH_ROWS", 1_000_000))
    TREES = int(os.environ.get("BENCH_TREES", 10))
    AUC_ITERS = int(os.environ.get("BENCH_AUC_ITERS", 100))
    N_TEST = 100_000
    if backend == "cpu":   # keep the CPU fallback quick
        N, TREES, AUC_ITERS, N_TEST = 50_000, 3, 20, 20_000

    X, y = make_data(N, 0)
    Xt, yt = make_data(N_TEST, 1)

    cfg = Config.from_dict({
        "objective": "binary",
        "num_leaves": 255,
        "max_bin": 63,            # GPU benchmark config (GPU-Performance.rst)
        "learning_rate": 0.1,
        "min_data_in_leaf": 20,
        "metric": "auc",
        "verbosity": -1,
        # batched frontier growth keeps the MXU busy (depthwise policy —
        # the same policy as xgboost_hist in the reference's comparison)
        "tree_growth": "levelwise",
    })
    ds = BinnedDataset.from_numpy(X, label=y, config=cfg)
    dt_test = BinnedDataset.from_numpy(Xt, label=yt, config=cfg, reference=ds)
    gbdt = create_boosting(cfg, ds)
    gbdt.add_valid(dt_test, "test")

    def sync():
        jax.device_get(gbdt._train_scores.score)

    # warmup: compiles the scanned multi-iteration step (same scan length
    # as the timed block — a different length would recompile).  The tunnel
    # adds run-to-run noise of up to ~30%, so every throughput number is the
    # best of 3 timed blocks (the block itself is a single device dispatch).
    gbdt.train_iters(TREES)
    sync()

    dt = 1e30
    for _ in range(3):
        t0 = time.time()
        gbdt.train_iters(TREES)
        sync()
        dt = min(dt, time.time() - t0)
    row_trees_per_s = N * TREES / dt / 1e6

    # the reference's own policy: leaf-wise (best-first), wave-batched
    # schedule with smaller-child subtraction (models/grower_wave.py), at
    # the default bf16x2 histogram precision.  bf16 single-pass histograms
    # are ~25% faster at 100-iter AUC parity but measurably lose AUC by
    # 500 iterations (0.9095 vs 0.9126 measured round 4), so the headline
    # stays at the precision that BEATS the reference's quality.
    cfg_lw = Config.from_dict({**{k: getattr(cfg, k) for k in (
        "objective", "num_leaves", "max_bin", "learning_rate",
        "min_data_in_leaf", "metric")}, "verbosity": -1,
        "tree_growth": "leafwise"})
    gb_lw = create_boosting(cfg_lw, ds)
    gb_lw.add_valid(dt_test, "test")
    lw_trees = TREES
    gb_lw.train_iters(lw_trees)
    jax.device_get(gb_lw._train_scores.score)
    lw_dt = 1e30
    for _ in range(3):
        t0 = time.time()
        gb_lw.train_iters(lw_trees)
        jax.device_get(gb_lw._train_scores.score)
        lw_dt = min(lw_dt, time.time() - t0)
    leafwise_mrt = N * lw_trees / lw_dt / 1e6
    remaining_lw = max(AUC_ITERS - gb_lw.iter, 0)
    if remaining_lw:
        gb_lw.train_iters(remaining_lw)
        jax.device_get(gb_lw._train_scores.score)
    leafwise_auc = None
    for (_, name, value, _) in gb_lw.eval_valid():
        if name == "auc":
            leafwise_auc = float(value)

    # quality: continue to AUC_ITERS total trees, eval held-out AUC
    remaining = max(AUC_ITERS - gbdt.iter, 0)
    if remaining:
        gbdt.train_iters(remaining)
        sync()
    auc = None
    for (_, name, value, _) in gbdt.eval_valid():
        if name == "auc":
            auc = float(value)
    # reference LightGBM (C++ CLI built from /root/reference, run on THIS
    # host, leaf-wise, same synthetic data/config): valid AUC and throughput
    # re-measured 2026-07-30 (round 4; machine idle, metric_freq=500 so the
    # timing is training-only like ours): 100 iters in 25.57 s, 500 iters in
    # 93.23 s train wall-clock.  Round 3's recorded 2.360 M row-trees/s is
    # superseded — the host was evidently contended then.
    auc_ref = 0.913227          # reference valid_1 auc at iteration 100
    ref_same_host_mrt = 3.911   # reference M row-trees/s, first 100 iters
    ref_500_wall_s = 93.23      # reference 500-iter training wall-clock
    ref_500_auc = 0.912632      # reference valid_1 auc at iteration 500

    extra = {}

    # ---- pipeline-overlap guard (async_wave_pipeline A/B) ----------------
    # The pipelined wave schedule (default) against the fully-serialized
    # legacy round body at the same config: the overlapped per-iter total
    # must not exceed the serialized one (plus tunnel noise).  On CPU the
    # backend serializes everything and the guard passes trivially — the
    # honest capture is the next device record.
    try:
        cfg_ser = Config.from_dict({**{k: getattr(cfg_lw, k) for k in (
            "objective", "num_leaves", "max_bin", "learning_rate",
            "min_data_in_leaf", "metric")}, "verbosity": -1,
            "tree_growth": "leafwise", "async_wave_pipeline": False})
        gb_ser = create_boosting(cfg_ser, ds)
        gb_ser.add_valid(dt_test, "test")
        gb_ser.train_iters(lw_trees)
        jax.device_get(gb_ser._train_scores.score)
        ser_dt = 1e30
        for _ in range(3):
            t0 = time.time()
            gb_ser.train_iters(lw_trees)
            jax.device_get(gb_ser._train_scores.score)
            ser_dt = min(ser_dt, time.time() - t0)
        pipe_ms = lw_dt / lw_trees * 1e3
        ser_ms = ser_dt / lw_trees * 1e3
        extra["pipeline_ms_per_iter"] = round(pipe_ms, 2)
        extra["pipeline_serialized_ms_per_iter"] = round(ser_ms, 2)
        extra["pipeline_overlap_ms"] = round(max(ser_ms - pipe_ms, 0.0), 2)
        extra["pipeline_ok"] = bool(backend == "cpu"
                                    or pipe_ms <= ser_ms * 1.05)
    except Exception as e:  # noqa: BLE001 — partial records beat none
        extra["pipeline_error"] = f"{type(e).__name__}: {e}"[:200]
        extra["pipeline_ok"] = False

    # ---- int8sr AUC-parity experiment (the hist_dtype_deep="auto" gate) --
    # Same data/config/iteration count as the headline leaf-wise AUC with
    # the stochastic-rounded int8 deep pass forced on; the "auto" flip to
    # int8sr on TPU is gated on a DEVICE capture of this block showing
    # auc_parity (|delta| <= 0.0005 — the tools/precision_expt.py bar).
    # quant_buckets_active records whether the gate actually engaged at
    # this shape (CPU smoke rows stay below the bucketing threshold).
    try:
        from lightgbmv1_tpu.models.grower_wave import (auto_wave_size,
                                                       slot_buckets_for)

        cfg_sr = Config.from_dict({**{k: getattr(cfg_lw, k) for k in (
            "objective", "num_leaves", "max_bin", "learning_rate",
            "min_data_in_leaf", "metric")}, "verbosity": -1,
            "tree_growth": "leafwise", "hist_dtype_deep": "int8sr"})
        gb_sr = create_boosting(cfg_sr, ds)
        gb_sr.add_valid(dt_test, "test")
        gb_sr.train_iters(lw_trees)
        jax.device_get(gb_sr._train_scores.score)
        sr_dt = 1e30
        for _ in range(3):
            t0 = time.time()
            gb_sr.train_iters(lw_trees)
            jax.device_get(gb_sr._train_scores.score)
            sr_dt = min(sr_dt, time.time() - t0)
        if gb_sr.iter < gb_lw.iter:      # AUC at the SAME tree count
            gb_sr.train_iters(gb_lw.iter - gb_sr.iter)
            jax.device_get(gb_sr._train_scores.score)
        sr_auc = None
        for (_, name, value, _) in gb_sr.eval_valid():
            if name == "auc":
                sr_auc = float(value)
        K_sr = auto_wave_size(cfg_sr.num_leaves)
        buckets = slot_buckets_for(K_sr, N)
        active = [int(S) for S in buckets if len(buckets) > 1
                  and ((S == K_sr and K_sr >= 32) or (S == 16 and S < K_sr))]
        delta = (None if sr_auc is None or leafwise_auc is None
                 else round(sr_auc - leafwise_auc, 6))
        extra["precision_expt"] = {"deep_int8sr": {
            "auc": round(sr_auc, 6) if sr_auc is not None else None,
            "auc_iters": int(gb_sr.iter),
            "auc_delta_vs_default": delta,
            "auc_parity": (None if delta is None
                           else bool(abs(delta) <= 0.0005)),
            "M_row_trees_per_s": round(N * lw_trees / sr_dt / 1e6, 3),
            "quant_buckets_active": active,
        }}
    except Exception as e:  # noqa: BLE001
        extra["precision_expt_error"] = f"{type(e).__name__}: {e}"[:200]

    # ---- fused wave-round megakernel A/B (hist_method=fused, ISSUE 13) --
    # Parity + throughput + compiled-executable HBM accounting on every
    # backend (CPU rides the interpreter lane); the perf leg of fused_ok
    # joins the device phase fields below.
    try:
        extra.update(measure_fused(ds, N, backend,
                                   n_iters=min(lw_trees, 3)))
    except Exception as e:  # noqa: BLE001 — partial records beat none
        extra["fused_error"] = f"{type(e).__name__}: {e}"[:200]
        extra["fused_parity_ok"] = False

    # ---- persistent multi-round wave loop A/B (wave_loop_rounds,
    # ISSUE 17): loop-vs-single-round parity + the VMEM plan + launch /
    # state-traffic accounting on every backend; the perf leg of
    # fused_loop_ok joins below.
    try:
        extra.update(measure_fused_waveloop(ds, N, backend,
                                            n_iters=min(lw_trees, 3)))
    except Exception as e:  # noqa: BLE001 — partial records beat none
        extra["fused_loop_error"] = f"{type(e).__name__}: {e}"[:200]
        extra["fused_loop_parity_ok"] = False

    # ---- 4-bit packed bins A/B (bin_layout=packed4, ISSUE 18): layout
    # parity at max_bin=15 + the binned-bytes halving, analytic and
    # measured; the packed_ok join lives below with the other guards.
    try:
        extra.update(measure_packed(X, y, backend,
                                    n_iters=min(lw_trees, 3)))
    except Exception as e:  # noqa: BLE001 — partial records beat none
        extra["packed_error"] = f"{type(e).__name__}: {e}"[:200]
        extra["packed_parity_ok"] = False

    if backend != "cpu" and os.environ.get("BENCH_FULL", "1") == "1":
        schedule = None
        try:
            schedule = probe_round_schedule(gb_lw)
        except Exception as e:  # noqa: BLE001 — partial records beat none
            extra["round_probe_error"] = f"{type(e).__name__}: {e}"[:200]
        if schedule is None:
            # degrade to the estimated frontier schedule, flagged, so the
            # record still carries hist_ms_per_iter + phase fields
            schedule = estimated_wave_schedule()
        hist_fields = {}
        try:
            hist_fields = measure_hist_and_roofline(ds, N, schedule)
            extra.update(hist_fields)
        except Exception as e:  # noqa: BLE001
            extra["hist_error"] = f"{type(e).__name__}: {e}"[:200]
        try:
            if schedule:
                extra.update(measure_phases(
                    ds, N, gb_lw, schedule, hist_fields, N_TEST,
                    per_iter_ms=lw_dt / lw_trees * 1e3))
        except Exception as e:  # noqa: BLE001
            extra["phase_error"] = f"{type(e).__name__}: {e}"[:200]

        # ---- phase_other attribution (the USE_TIMETAG discipline applied
        # to the residual): decompose phase_other_ms into named sub-phases
        # with the same differential methodology, priced over the replayed
        # round schedule; the record flags any unattributed remainder
        # above 10% of the measured per-iteration wall so the residual can
        # never silently regrow (tools/phase_attrib.py).
        try:
            if "phase_other_ms" in extra:
                from lightgbmv1_tpu.models.grower_wave import (
                    auto_wave_size, slot_buckets_for)
                from tools.phase_attrib import measure_other_breakdown

                K_att = auto_wave_size(255)
                rounds = schedule["schedule"]
                iters = max(1, round(len(rounds)
                                     / schedule["rounds_per_tree"]))
                bd = measure_other_breakdown(
                    N=N, F=28, B=64, L=255, K=K_att,
                    rounds_per_iter=len(rounds) / iters,
                    n_buckets=len(slot_buckets_for(K_att, N)),
                    n_valid=N_TEST, num_class=1,
                    objective=gb_lw.objective,
                    fused=cfg_lw.fused_bookkeeping)
                extra.update(bd.record(
                    extra["phase_other_ms"],
                    extra["phase_total_measured_ms"]))
        except Exception as e:  # noqa: BLE001
            extra["phase_attrib_error"] = f"{type(e).__name__}: {e}"[:200]

        # ---- split-phase burn-down attribution: decompose the measured
        # phase_split_ms into the fused scan's named stages (ops/split.py
        # scan_left_sums / scan_direction_gains / scan_pick — the REAL
        # code objects, timed at bench shapes over the replayed schedule)
        # so the 22.8 ms r05 target is attributable per-component.
        try:
            if "phase_split_ms" in extra:
                from lightgbmv1_tpu.models.grower_wave import auto_wave_size
                from tools.phase_attrib import measure_split_breakdown

                rounds_s = schedule["schedule"]
                iters_s = max(1, round(len(rounds_s)
                                       / schedule["rounds_per_tree"]))
                sbd = measure_split_breakdown(
                    F=28, B=64, K=auto_wave_size(255),
                    rounds_per_iter=len(rounds_s) / iters_s,
                    meta=gb_lw.meta, params=gb_lw.split_params)
                extra["phase_split_breakdown"] = dict(sbd.parts)
                extra["phase_split_unattributed_ms"] = round(
                    extra["phase_split_ms"] - sbd.total_attributed(), 3)
        except Exception as e:  # noqa: BLE001
            extra["split_attrib_error"] = f"{type(e).__name__}: {e}"[:200]

        # ---- fused wave round, measured (ISSUE 13 + 15): the merged
        # pass per bucket priced over the replayed schedule — the
        # label-input kernel (hist_split_fused_ms_per_iter, the
        # fused_ok perf leg) AND the routed single-pass round with
        # partition folded in (partition_fused_ms_per_iter, the
        # fused_round_ok leg bench_trend watches).  A capture training
        # with hist_method=fused would carry the routed number as its
        # phase row (phase_round_fused_ms,
        # tools/phase_attrib.PHASE_MS_KEYS).
        try:
            if schedule:
                extra.update(measure_fused_round_ms(
                    ds, N, gb_lw, schedule, hist_fields, backend))
        except Exception as e:  # noqa: BLE001
            extra["fused_round_error"] = f"{type(e).__name__}: {e}"[:200]

        # DART per-iteration cost (fused single-dispatch iteration):
        # VERDICT r3 #7 asks this within ~2x of the scanned GBDT path
        try:
            cfg_dart = Config.from_dict({
                "objective": "binary", "boosting": "dart", "num_leaves": 255,
                "max_bin": 63, "learning_rate": 0.1, "min_data_in_leaf": 20,
                "drop_rate": 0.1, "verbosity": -1,
                "tree_growth": "leafwise"})
            gbd = create_boosting(cfg_dart, ds)
            for _ in range(8):   # warm the no-drop and P-bucket variants
                gbd.train_one_iter(check_stop=False)
            sync_d = lambda: jax.device_get(gbd._train_scores.score)
            sync_d()
            DIT = 15
            t0 = time.time()
            for _ in range(DIT):
                gbd.train_one_iter(check_stop=False)
            sync_d()
            dart_dt = time.time() - t0
            dart_mrt = N * DIT / dart_dt / 1e6
            extra["dart_M_row_trees_per_s"] = round(dart_mrt, 3)
            # denominator = the SCANNED LEAF-WISE number the name promises
            # (VERDICT r4 weak #4: this once divided by the level-wise
            # block's throughput); note DART here is timed per-iteration
            # dispatch while the denominator block is scanned, so the
            # ratio carries ~113 ms/iter of tunnel dispatch against DART
            extra["dart_frac_of_scanned_gbdt"] = round(
                dart_mrt / max(leafwise_mrt, 1e-9), 3)
        except Exception as e:  # noqa: BLE001
            extra["dart_error"] = f"{type(e).__name__}: {e}"[:200]

        # GOSS and RF fused-scan rows (VERDICT r5 #7): both modes ride the
        # same lax.scan single-dispatch block as plain GBDT (PERF.md
        # "boosting-mode dispatch costs") — these rows put a measured
        # number behind that claim at the bench shapes
        for bname, bover in (
                ("goss", {"boosting": "goss"}),
                ("rf", {"boosting": "rf", "bagging_fraction": 0.63,
                        "bagging_freq": 1})):
            try:
                cfg_b = Config.from_dict({
                    "objective": "binary", "num_leaves": 255, "max_bin": 63,
                    "learning_rate": 0.1, "min_data_in_leaf": 20,
                    "verbosity": -1, "tree_growth": "leafwise", **bover})
                gbb = create_boosting(cfg_b, ds)
                gbb.train_iters(TREES)
                jax.device_get(gbb._train_scores.score)
                b_dt = 1e30
                for _ in range(3):
                    t0 = time.time()
                    gbb.train_iters(TREES)
                    jax.device_get(gbb._train_scores.score)
                    b_dt = min(b_dt, time.time() - t0)
                extra[f"{bname}_M_row_trees_per_s"] = round(
                    N * TREES / b_dt / 1e6, 3)
            except Exception as e:  # noqa: BLE001
                extra[f"{bname}_error"] = f"{type(e).__name__}: {e}"[:200]

        # prediction benchmark row (VERDICT r5 #6): native C++ predictor +
        # device batch walk, file->file on the bench set with the 100-tree
        # leaf-wise model (gb_lw has >= AUC_ITERS trees by this point)
        try:
            extra.update(measure_predict(gb_lw, X))
        except Exception as e:  # noqa: BLE001
            extra["predict_error"] = f"{type(e).__name__}: {e}"[:200]

        # ---- parity set beyond binary (VERDICT r4 missing #1): the
        # reference publishes multiclass and ranking rows in
        # docs/Experiments.rst:113-151; golden tests prove these families
        # CORRECT — these blocks put speed + quality on record against the
        # same-host reference binary at matched configs (constants
        # measured with tools/measure_ref_parity.py, 1 core, idle host,
        # training-only timing via metric_freq=<iters>)
        try:
            MC_N, MC_CLS, MC_IT = 250_000, 5, 50
            Xm, ym = make_multiclass_data(MC_N, 10, MC_CLS)
            Xmv, ymv = make_multiclass_data(50_000, 11, MC_CLS)
            cfg_mc = Config.from_dict({
                "objective": "multiclass", "num_class": MC_CLS,
                "num_leaves": 127, "max_bin": 63, "learning_rate": 0.1,
                "min_data_in_leaf": 20, "metric": "multi_logloss",
                "verbosity": -1, "tree_growth": "leafwise"})
            dsm = BinnedDataset.from_numpy(Xm, label=ym, config=cfg_mc)
            dsmv = BinnedDataset.from_numpy(Xmv, label=ymv, config=cfg_mc,
                                            reference=dsm)
            gbm = create_boosting(cfg_mc, dsm)
            gbm.add_valid(dsmv, "test")
            # warm-up block has the SAME scan length as the timed blocks —
            # a different length would recompile inside the timed window
            BLK = MC_IT // 2
            gbm.train_iters(BLK)
            jax.device_get(gbm._train_scores.score)
            gbm.train_iters(BLK)          # to MC_IT trees for the quality
            jax.device_get(gbm._train_scores.score)   # read (ref parity)
            mll = None   # quality read at exactly MC_IT trees (ref parity)
            for (_, name, value, _) in gbm.eval_valid():
                if name == "multi_logloss":
                    mll = float(value)
            # throughput from ONE LONG window (the binary block's 500-iter
            # methodology applied here): the old best-of-3 25-iter windows
            # recorded 2x tunnel-drift swings minutes apart — a 100-iter
            # wall of scanned single-dispatch blocks amortizes the drift
            # the way the stable 500-iter binary number does
            MC_WIN = 4
            t0 = time.time()
            for _ in range(MC_WIN):
                gbm.train_iters(BLK)
            jax.device_get(gbm._train_scores.score)
            mc_dt = time.time() - t0
            mc_mrt = MC_N * BLK * MC_WIN * MC_CLS / mc_dt / 1e6
            extra["multiclass_M_row_trees_per_s"] = round(mc_mrt, 3)
            extra["multiclass_window_iters"] = BLK * MC_WIN
            extra["multiclass_logloss"] = (round(mll, 5)
                                           if mll is not None else None)
            # reference C++ on THIS host, same data/config (recorded by
            # tools/measure_ref_parity.py)
            if REF_MC_M_ROW_TREES_S:
                extra["multiclass_ref_cpp_M_row_trees_per_s"] = \
                    REF_MC_M_ROW_TREES_S
                extra["multiclass_vs_ref_same_host"] = round(
                    mc_mrt / REF_MC_M_ROW_TREES_S, 4)
                extra["multiclass_ref_cpp_logloss"] = REF_MC_LOGLOSS
        except Exception as e:  # noqa: BLE001
            extra["multiclass_error"] = f"{type(e).__name__}: {e}"[:200]

        try:
            RK_Q, RK_D, RK_IT = 2000, 100, 100
            Xr, yr, gr = make_rank_data(RK_Q, RK_D, 20)
            Xrv, yrv, grv = make_rank_data(400, RK_D, 21)
            cfg_rk = Config.from_dict({
                "objective": "lambdarank", "num_leaves": 63, "max_bin": 63,
                "learning_rate": 0.1, "min_data_in_leaf": 20,
                "metric": "ndcg", "eval_at": [10], "verbosity": -1,
                "tree_growth": "leafwise"})
            dsr = BinnedDataset.from_numpy(Xr, label=yr, group=gr,
                                           config=cfg_rk)
            dsrv = BinnedDataset.from_numpy(Xrv, label=yrv, group=grv,
                                            config=cfg_rk, reference=dsr)
            gbr = create_boosting(cfg_rk, dsr)
            gbr.add_valid(dsrv, "test")
            # same-scan-length warm-up, then ONE LONG window (see the
            # multiclass block: the old best-of-3 short windows drifted 2x)
            BLKR = RK_IT // 4
            for _ in range(4):            # warm + reach RK_IT trees for the
                gbr.train_iters(BLKR)     # quality read (ref parity)
            jax.device_get(gbr._train_scores.score)
            ndcg = None
            for (_, name, value, _) in gbr.eval_valid():
                if "ndcg" in name:
                    ndcg = float(value)
            RK_WIN = 6
            t0 = time.time()
            for _ in range(RK_WIN):
                gbr.train_iters(BLKR)
            jax.device_get(gbr._train_scores.score)
            rk_dt = time.time() - t0
            rk_mrt = RK_Q * RK_D * BLKR * RK_WIN / rk_dt / 1e6
            extra["rank_M_row_trees_per_s"] = round(rk_mrt, 3)
            extra["rank_window_iters"] = BLKR * RK_WIN
            extra["rank_ndcg10"] = round(ndcg, 5) if ndcg is not None else None
            if REF_RK_M_ROW_TREES_S:
                extra["rank_ref_cpp_M_row_trees_per_s"] = REF_RK_M_ROW_TREES_S
                extra["rank_vs_ref_same_host"] = round(
                    rk_mrt / REF_RK_M_ROW_TREES_S, 4)
                extra["rank_ref_cpp_ndcg10"] = REF_RK_NDCG10
        except Exception as e:  # noqa: BLE001
            extra["rank_error"] = f"{type(e).__name__}: {e}"[:200]

        # 500-tree north star (docs/Experiments.rst:110-135 methodology on
        # this host's data): reference side measured with the same binary
        # the goldens use; our side timed over trees 100..500 (the first
        # 100 run under compile) and scaled to 500
        try:
            gb5 = create_boosting(cfg_lw, ds)
            gb5.add_valid(dt_test, "test")
            gb5.train_iters(100)
            jax.device_get(gb5._train_scores.score)
            t0 = time.time()
            for _ in range(4):
                gb5.train_iters(100)
            jax.device_get(gb5._train_scores.score)
            wall400 = time.time() - t0
            wall500 = wall400 * 500.0 / 400.0
            auc500 = None
            for (_, name, value, _) in gb5.eval_valid():
                if name == "auc":
                    auc500 = float(value)
            extra["tpu_500iter_wall_s"] = round(wall500, 2)
            extra["tpu_500iter_auc"] = (round(auc500, 6)
                                        if auc500 is not None else None)
            extra["ref_cpp_500iter_wall_s"] = ref_500_wall_s
            extra["ref_cpp_500iter_auc"] = ref_500_auc
            extra["vs_ref_500iter"] = round(ref_500_wall_s / wall500, 4)
        except Exception as e:  # noqa: BLE001
            extra["northstar_error"] = f"{type(e).__name__}: {e}"[:200]

    # ---- fused_ok (ISSUE 13): parity AND, on device, the measured
    # fused round at or under the staged hist+split it replaces.  The
    # staged path stays the default until a device capture lands this
    # guard True with the ms comparison actually evaluated (a CPU
    # capture proves parity only — the perf leg is trivially true
    # there, like pipeline_ok).
    fused_ms = extra.get("hist_split_fused_ms_per_iter")
    staged_ms = ((extra.get("phase_hist_ms") or 0)
                 + (extra.get("phase_split_ms") or 0))
    extra["fused_ok"] = bool(
        extra.get("fused_parity_ok")
        and (backend == "cpu"
             or (fused_ms is not None and staged_ms > 0
                 and fused_ms <= staged_ms)))

    # ---- fused_round_ok (ISSUE 15): the single-pass wave round —
    # routed parity (the measure_fused A/B trains through the in-kernel
    # partition + valid routing + top-k dispatch) AND the single-read
    # bytes contract: analytically the binned matrix is touched once
    # per round, and on device the compiled round executables must show
    # >= 1.8x fewer bytes than the staged partition+hist they replace
    # (the CPU interpreter's block-copy accounting is unrepresentative
    # — fused_bytes_interpret_mode — so the CPU record carries the
    # parity + analytic legs only, like fused_ok's perf leg).
    fr_red = extra.get("fused_round_bytes_reduction")
    extra["fused_round_ok"] = bool(
        extra.get("fused_parity_ok")
        and extra.get("fused_round_single_read_ok")
        and (backend == "cpu"
             or (fr_red is not None and fr_red >= 1.8
                 and extra.get("partition_fused_ms_per_iter")
                 is not None)))

    # ---- fused_loop_ok (ISSUE 17): the persistent multi-round wave
    # loop — loop-vs-single-round model-text parity everywhere AND, on
    # device, the looped per-iteration wall at or under the single-round
    # fused wall it replaces (the boundary saving must not be negative;
    # a CPU capture proves parity only — the interpreter serializes the
    # grid, so its wall is unrepresentative, like fused_ok's perf leg).
    # The staged path stays the default until a device capture lands
    # this guard True with the ms leg actually evaluated.
    lp_save = extra.get("wave_loop_boundary_saving_ms_per_iter")
    extra["fused_loop_ok"] = bool(
        extra.get("fused_loop_parity_ok")
        and (backend == "cpu"
             or (lp_save is not None and lp_save >= 0)))
    # the watched phase row: the loop dispatch priced by the
    # differential method — the measured single-round dispatch ms minus
    # the boundary saving the loop run demonstrated
    pfm = extra.get("partition_fused_ms_per_iter")
    if pfm is not None and lp_save is not None:
        extra["phase_wave_loop_ms"] = round(max(pfm - lp_save, 0.0), 3)

    # ---- packed_ok (ISSUE 18): 4-bit packed bins — four-way layout
    # parity (packed/unpacked x fused/staged, model text byte-compared)
    # AND the analytic >= 1.9x binned-read reduction AND, on device, the
    # compiled hist executables showing >= 1.5x fewer bytes on packed
    # input (the CPU interpreter's block-copy accounting is
    # unrepresentative — packed_bytes_interpret_mode — so the CPU record
    # carries the parity + analytic legs only, like fused_round_ok).
    pk_red = extra.get("packed_hist_bytes_reduction")
    extra["packed_ok"] = bool(
        extra.get("packed_parity_ok")
        and (extra.get("packed_binned_bytes_reduction") or 0) >= 1.9
        and (backend == "cpu"
             or (pk_red is not None and pk_red >= 1.5)))

    # Online-serving loadgen block (serve/ subsystem): runs on every
    # backend — the acceptance record for hot-swap-under-traffic and
    # bounded-queue shedding is explicitly a CPU loadgen run; on device
    # sessions the same block prices the micro-batched device walk.
    try:
        extra.update(measure_serve(gb_lw, X))
    except Exception as e:  # noqa: BLE001 — partial records beat none
        extra["serve_error"] = f"{type(e).__name__}: {e}"[:200]
        extra["serve_ok"] = False

    # Fault-tolerant fleet block (ISSUE 11): replica-kill under loadgen
    # with zero client-visible errors, coordinated two-phase publish,
    # and the elastic kill-resume byte-parity drill — on every backend.
    try:
        extra.update(measure_fleet(gb_lw, X))
    except Exception as e:  # noqa: BLE001
        extra["fleet_error"] = f"{type(e).__name__}: {e}"[:200]
        extra["fleet_ok"] = False

    # Multi-tenant serving block (ISSUE 20): compile-bucket sharing
    # proven by per-label counters, fair-share isolation under a hot-
    # tenant overload, per-tenant publish/rollback parity, and the
    # SLO-driven placement-move drill — on every backend.
    try:
        extra.update(measure_tenants(gb_lw, X))
    except Exception as e:  # noqa: BLE001
        extra["tenant_error"] = f"{type(e).__name__}: {e}"[:200]
        extra["tenant_ok"] = False

    # Robustness block (PR 6): the scripted chaos suite on every backend
    # — every injected fault (kill/torn-file/NaN/stall/garbage-publish/
    # overload/transient-H2D) must be recovered or the record flags it.
    try:
        extra.update(measure_chaos())
    except Exception as e:  # noqa: BLE001
        extra["chaos_error"] = f"{type(e).__name__}: {e}"[:200]
        extra["chaos_ok"] = False
        extra["chaos_fleet_ok"] = False

    # Out-of-core streaming block (PR 8, data/ subsystem): block cache +
    # row-block trainer vs the resident trainer — byte parity AND the
    # bounded-device-memory ledger guard, on every backend.
    try:
        extra.update(measure_stream(X, y, backend))
    except Exception as e:  # noqa: BLE001
        extra["stream_error"] = f"{type(e).__name__}: {e}"[:200]
        extra["stream_ok"] = False

    # Observability block (ISSUE 9): the obs/ layer measures ITSELF —
    # armed-tracer A/B overhead vs the 2% contract with off-path model
    # bit-parity, train/serve Chrome-trace validity (train spans agree
    # with the phase_attrib fields measured above via the installed
    # profile), and Prometheus exposition health — on every backend.
    try:
        extra.update(measure_obs(X, y, backend, phase_fields=extra))
    except Exception as e:  # noqa: BLE001
        extra["obs_error"] = f"{type(e).__name__}: {e}"[:200]
        extra["obs_ok"] = False

    # Model-quality & data-drift block (ISSUE 14): the deterministic
    # skew-injection probe (clean traffic quiet, injected shift
    # detected), the streamed-vs-resident reference byte-parity check,
    # the armed-sampling <= 2% serving overhead A/B, and the trainer
    # quality telemetry summary — on every backend.
    try:
        extra.update(measure_drift(X, y, backend))
    except Exception as e:  # noqa: BLE001
        extra["drift_error"] = f"{type(e).__name__}: {e}"[:200]
        extra["drift_ok"] = False

    # Cross-chip comm pricing (analytic, parallel/cluster.py — the same
    # single-source formula the trainer logs and dryrun_multichip
    # records): the BENCH shape's per-round comm table at the MULTICHIP
    # smoke pod width (D=8), so the record carries bench-shape byte
    # figures next to the smoke-shape ones PERF.md renders.  Purely
    # shape+dtype arithmetic — no device needed, so it runs on the CPU
    # fallback too.
    try:
        from lightgbmv1_tpu.models.grower_wave import auto_wave_size
        from lightgbmv1_tpu.parallel.cluster import (comm_table_per_round,
                                                     hier_comm_ok,
                                                     hier_comm_table_per_round)

        K_comm = auto_wave_size(cfg_lw.num_leaves)
        extra["comm_bytes_per_round_d8"] = {
            mode: comm_table_per_round("data", mode, k=K_comm, F=28, B=64,
                                       ndev=8)
            for mode in ("reduce_scatter", "allreduce")}
        # the voting learner's table rides too, so the record prices the
        # top-2k ELECTION payload (vote_bytes) next to the selective
        # reduce it buys — the vote vector never rides uncounted
        extra["comm_bytes_per_round_d8"]["voting"] = comm_table_per_round(
            "voting", "reduce_scatter", k=K_comm, F=28, B=64, ndev=8,
            sel_k=min(2 * 20, 28))
        # pod-scale two-level pricing (ISSUE 16) at the same shape on the
        # 2x4 smoke pod, split by level (ICI vs DCN), with the
        # hier_comm_ok guard: DCN histogram bytes <= flat wire / hosts,
        # voting additionally <= its top-2k analytic bound
        hier = {
            ln: hier_comm_table_per_round(
                ln, k=K_comm, F=28, B=64, ndev=8, num_hosts=2,
                sel_k=min(2 * 20, 28) if ln == "voting" else None)
            for ln in ("data", "voting")}
        extra["hier_comm_bytes_per_round"] = hier
        extra["hier_dcn_hist_bytes"] = hier["data"]["dcn"]["hist_bytes"]
        extra["hier_comm_ok"] = (
            hier_comm_ok(hier["data"]["dcn"]["hist_bytes"],
                         hier["data"]["flat_hist_wire_bytes"], 2)
            and hier_comm_ok(hier["voting"]["dcn"]["hist_bytes"],
                             hier["voting"]["flat_hist_wire_bytes"], 2,
                             vote_bound_bytes=hier["voting"]
                             ["flat_hist_wire_bytes"]))
    except Exception as e:  # noqa: BLE001
        extra["comm_error"] = f"{type(e).__name__}: {e}"[:200]
        extra["hier_comm_ok"] = False

    baseline = 10.5e6 * 500 / 130.094 / 1e6   # reference CPU HIGGS throughput
    print(json.dumps({
        # headline = leaf-wise (the reference's own growth policy), bf16
        # device histograms (the reference's GPU-benchmark precision choice)
        "metric": f"higgs-shaped binary training throughput, leaf-wise "
                  f"({backend}, {N} rows, 28 feat, 63 bins, 255 leaves)",
        "value": round(leafwise_mrt, 3),
        "unit": "M row-trees/s",
        "vs_baseline": round(leafwise_mrt / baseline, 4),
        "auc": (round(leafwise_auc, 5)
                if leafwise_auc is not None else None),
        "auc_ref_lightgbm_cpp": auc_ref,
        # auc_iters fields record the ACTUAL tree counts behind each auc —
        # with BENCH_TREES overridden high the timed blocks can overshoot
        # AUC_ITERS, making the ref comparison no longer like-for-like
        "auc_iters": int(gb_lw.iter),
        # the reference C++ CLI measured on THIS host's CPU (the 40.36 M
        # row-trees/s baseline machine is a 28-core dual-Xeon; see PERF.md)
        "ref_cpp_same_host_M_row_trees_per_s": ref_same_host_mrt,
        "vs_ref_same_host": round(leafwise_mrt / ref_same_host_mrt, 4),
        "levelwise_M_row_trees_per_s": round(row_trees_per_s, 3),
        "levelwise_auc": round(auc, 5) if auc is not None else None,
        "levelwise_auc_iters": int(gbdt.iter),
        "levelwise_vs_ref_same_host": round(
            row_trees_per_s / ref_same_host_mrt, 4),
        "train_seconds_for_timed_block": round(lw_dt, 3),
        **extra,
    }))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:   # noqa: BLE001 — the driver records stdout; a
        # crash must still leave a parseable record of what happened
        import traceback

        traceback.print_exc()
        print(json.dumps({
            "metric": "higgs-shaped binary training throughput (FAILED)",
            "value": 0.0,
            "unit": "M row-trees/s",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}"[:300],
        }))
        sys.exit(1)   # truthful exit code alongside the parseable record
