"""One-command driver capture: arm, run, merge, record, gate.

Every device capture before ISSUE 12 was a hand-run session: bench here,
dryrun there, artifacts scattered, records assembled by copy-paste, the
gate run (or forgotten) afterwards.  This harness makes ROADMAP item 2's
capture campaign an executable procedure:

1. **Arm** — the XLA profiler (obs/xla.py ``profiler_session`` — device
   lane + wall-clock anchor sidecar) and the span tracer around a
   dedicated profiled training window whose host artifacts (trace /
   metrics / events) are exported next to the capture.
2. **Run** — ``bench.py`` (ALL blocks: train/predict/serve/chaos/stream/
   fleet/obs incl. the new device-truth block) and the
   ``__graft_entry__.py`` smoke battery (compile-check + serve_smoke +
   chaos_smoke + ``dryrun_multichip``), each as a subprocess with
   ``LGBMV1_OBS_DIR`` pointed at the capture's artifact directory.
3. **Merge** — every artifact + the profiler capture into ONE Perfetto
   trace (obs/agg.py ``aggregate_dir(profile_dir=...)``): host span
   lanes, per-process metric/event artifacts and the device lane on one
   wall-clock axis, estimated phase spans reconciled against measured
   ``lgbm.*`` device rows (agreement ratio recorded).  The merged trace
   is schema-validated (:func:`validate_merged_trace`).
4. **Record** — ``BENCH_rNN.json`` / ``MULTICHIP_rNN.json`` in the
   repo's captured-record format ({n, cmd, rc, tail, parsed}).
5. **Gate** — ``tools/ci_gate.py`` with ``--require-guards default``
   (every ``*_ok`` the record must carry, incl. ``obs_device_ok``).

Usage::

    python tools/capture.py                  # real capture: records into
                                             # the repo, gate vs priors
    python tools/capture.py --dry-run        # CPU rehearsal: records into
                                             # a scratch dir, gated in
                                             # isolation (no priors), repo
                                             # records untouched
    python tools/capture.py --out DIR        # keep artifacts in DIR
    python tools/capture.py --flip-defaults  # rehearse ROADMAP item 1's
                                             # default flip (fused + auto
                                             # deep dtype): parity battery
                                             # + required-guards gate

Exit 0 only when every stage ran AND the gate passed.  Prints one JSON
summary line last.  ``run_capture`` is the library entry (tests drive it
with stubbed stage commands).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import subprocess
import sys
import tempfile
import time

TOOLS = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(TOOLS)
for p in (ROOT, TOOLS):
    if p not in sys.path:
        sys.path.insert(0, p)

TAIL_BYTES = 40_000


def next_round(records_dir: str) -> int:
    """1 + the highest round among BENCH_r*/MULTICHIP_r* records."""
    best = 0
    for path in glob.glob(os.path.join(records_dir, "*_r*.json")):
        m = re.search(r"_r(\d+)\.json$", path)
        if m:
            best = max(best, int(m.group(1)))
    return best + 1


def run_stage(cmd, env=None, timeout_s: float = 7200.0) -> dict:
    """Run one capture stage as a subprocess; returns the record-shaped
    ``{cmd, rc, tail, parsed}`` dict (``parsed`` is the LAST JSON object
    line of stdout, the bench convention; None when none parses)."""
    t0 = time.time()
    try:
        proc = subprocess.run(
            cmd, cwd=ROOT, env=env, timeout=timeout_s,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        out = proc.stdout.decode("utf-8", "replace")
        rc = proc.returncode
    except subprocess.TimeoutExpired as e:
        out = (e.stdout or b"").decode("utf-8", "replace") + "\nTIMEOUT"
        rc = 124
    parsed = None
    for line in reversed(out.splitlines()):
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                parsed = json.loads(line)
                break
            except ValueError:
                continue
    return {"cmd": " ".join(map(str, cmd)), "rc": rc,
            "tail": out[-TAIL_BYTES:], "parsed": parsed,
            "seconds": round(time.time() - t0, 1)}


def profiled_window(out_dir: str, rows: int = 4096, iters: int = 3) -> dict:
    """The dedicated profiled training window: a small train under the
    armed XLA profiler + span tracer (phase profile installed so the
    estimated spans exist for the reconciliation), exporting this
    process's host artifacts into ``out_dir`` and the device capture
    into ``out_dir``/device.  Small by design — the heavyweight numbers
    come from bench.py; this window exists to light up the device lane."""
    import numpy as np

    import lightgbmv1_tpu as lgb
    from lightgbmv1_tpu.obs import agg as obs_agg
    from lightgbmv1_tpu.obs import trace as obs_trace
    from lightgbmv1_tpu.obs import xla as obs_xla

    prof_dir = os.path.join(out_dir, "device")
    art_dir = os.path.join(out_dir, "obs")
    os.makedirs(art_dir, exist_ok=True)
    rng = np.random.RandomState(0)
    X = rng.randn(int(rows), 8)
    y = (X[:, 0] - X[:, 1] + 0.5 * X[:, 2] > 0).astype(float)
    params = {"objective": "binary", "num_leaves": 31, "max_bin": 63,
              "verbosity": -1, "seed": 5}
    obs_trace.reset()
    obs_trace.arm(ring_events=1 << 15)
    # a nominal profile so iteration spans carry estimated phase
    # children for the device-row reconciliation to grade
    obs_trace.set_phase_profile(
        {"hist": 1.0, "partition": 0.5, "split": 0.3}, 4.0)
    try:
        with obs_xla.profiler_session(prof_dir):
            ds = lgb.Dataset(X, label=y, params=dict(params))
            lgb.train(dict(params), ds, num_boost_round=int(iters),
                      verbose_eval=False)
        paths = obs_agg.export_process_artifacts(art_dir, label="capture")
    finally:
        obs_trace.reset()
    return {"profile_dir": prof_dir, "artifact_dir": art_dir,
            "artifacts": sorted(paths)}


def validate_merged_trace(path: str) -> dict:
    """Schema validation of a merged Chrome trace: a JSON object with a
    ``traceEvents`` list whose complete events carry name/ph/ts/dur/pid
    with non-negative clocks, plus the merge provenance otherData.
    Raises ValueError on any violation; returns summary counts."""
    with open(path) as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        raise ValueError("merged trace: not a Chrome trace document")
    other = doc.get("otherData") or {}
    if not isinstance(other.get("sources"), list) or not other["sources"]:
        raise ValueError("merged trace: missing merge provenance")
    lanes = set()
    n_complete = 0
    for ev in doc["traceEvents"]:
        ph = ev.get("ph")
        if ph == "X":
            n_complete += 1
            if not ev.get("name") or "pid" not in ev:
                raise ValueError(f"merged trace: malformed event {ev!r}")
            if float(ev.get("ts", -1)) < 0 or float(ev.get("dur", -1)) < 0:
                raise ValueError(
                    f"merged trace: negative clock in {ev.get('name')!r}")
            lanes.add(ev["pid"])
    if not n_complete:
        raise ValueError("merged trace: no complete events")
    return {"events": n_complete, "lanes": len(lanes),
            "sources": len(other["sources"]),
            "phase_agreement": other.get("phase_agreement") or {}}


def run_capture(records_dir: str = ROOT, out_dir: str = None,
                round_no: int = None, dry_run: bool = False,
                bench_cmd=None, smoke_cmd=None, skip_t1: bool = True,
                t1_log: str = "/tmp/_t1.log", window_rows: int = 4096,
                stage_timeout_s: float = 7200.0, out=print) -> dict:
    """The full capture pipeline (module docstring).  ``dry_run`` writes
    the records into a SCRATCH records dir and gates them in isolation —
    the repo's captured history is never touched by a rehearsal.
    ``bench_cmd``/``smoke_cmd`` override the stage commands (tests stub
    them); ``skip_t1`` passes through to the gate (a capture box has no
    tier-1 log unless the suite just ran)."""
    import ci_gate  # noqa: E402 — sibling tool, path set above

    out_dir = out_dir or tempfile.mkdtemp(prefix="capture_")
    os.makedirs(out_dir, exist_ok=True)
    rec_out = (tempfile.mkdtemp(prefix="capture_records_")
               if dry_run else records_dir)
    n = round_no if round_no is not None else next_round(records_dir)
    summary = {"round": n, "out_dir": out_dir, "records_dir": rec_out,
               "dry_run": bool(dry_run), "ok": False}

    # 1. armed profiled window (device lane + host artifacts)
    window = profiled_window(out_dir, rows=window_rows)
    summary["window"] = window
    art_dir = window["artifact_dir"]

    env = dict(os.environ)
    env["LGBMV1_OBS_DIR"] = art_dir
    env.setdefault("LGBMV1_RUN_ID", f"capture_r{n:02d}")

    # 2. bench (all blocks) + the smoke battery (entry/serve/chaos/dryrun)
    bench_cmd = bench_cmd or [sys.executable, "bench.py"]
    smoke_cmd = smoke_cmd or [sys.executable, "__graft_entry__.py"]
    out(f"capture: running bench stage: {' '.join(map(str, bench_cmd))}")
    bench = run_stage(bench_cmd, env=env, timeout_s=stage_timeout_s)
    out(f"capture: bench rc={bench['rc']} in {bench['seconds']}s")
    out(f"capture: running smoke stage: {' '.join(map(str, smoke_cmd))}")
    smoke = run_stage(smoke_cmd, env=env, timeout_s=stage_timeout_s)
    out(f"capture: smokes rc={smoke['rc']} in {smoke['seconds']}s")
    summary["bench_rc"] = bench["rc"]
    summary["smoke_rc"] = smoke["rc"]

    # 3. merge every artifact + the device capture into one trace
    from lightgbmv1_tpu.obs import agg as obs_agg

    agg_summary = obs_agg.aggregate_dir(
        art_dir, profile_dir=window["profile_dir"])
    try:
        summary["merged_trace"] = validate_merged_trace(
            agg_summary["merged_trace"])
        summary["merged_trace"]["path"] = agg_summary["merged_trace"]
        trace_ok = True
    except ValueError as e:
        summary["merged_trace_error"] = str(e)
        trace_ok = False
    summary["device_lanes"] = agg_summary.get("device_lanes", 0)
    summary["phase_agreement"] = agg_summary.get("phase_agreement") or {}

    # 4. emit the records in the captured format
    def write_record(name: str, stage: dict) -> str:
        path = os.path.join(rec_out, name)
        doc = {"n": n, "cmd": stage["cmd"], "rc": stage["rc"],
               "tail": stage["tail"]}
        if stage.get("parsed") is not None:
            doc["parsed"] = stage["parsed"]
        with open(path, "w") as fh:
            json.dump(doc, fh, indent=1)
        return path

    summary["bench_record"] = write_record(f"BENCH_r{n:02d}.json", bench)
    summary["multichip_record"] = write_record(
        f"MULTICHIP_r{n:02d}.json", smoke)

    # 5. gate: trend + required guards (+ tier-1 budget when a log exists)
    gate = ci_gate.run_gate(
        rec_out, t1_log, skip_t1=skip_t1,
        require_guards=ci_gate.REQUIRED_GUARDS, out=out)
    summary["gate"] = gate
    summary["ok"] = bool(bench["rc"] == 0 and smoke["rc"] == 0
                         and trace_ok and gate["ok"])
    return summary


# ROADMAP item 1's endgame knobs: what the device capture flips
# default-on once the fused guards land green.  One place, so the
# rehearsal and the real flip can never drift apart.
FLIP_DEFAULTS = {"hist_method": "fused", "hist_dtype_deep": "auto"}


def run_flip_rehearsal(records_dir: str = ROOT, iters: int = 3,
                       out=print) -> dict:
    """``--flip-defaults``: the ROADMAP item 1 default-flip rehearsal as
    ONE flag instead of a hand-assembled session.

    Trains the parity battery — binary / multiclass / DART, plus the
    ``wave_loop_rounds>1`` persistent-loop leg (ISSUE 17) — UNDER the
    flipped defaults (``FLIP_DEFAULTS``: ``hist_method=fused`` +
    ``hist_dtype_deep=auto``), each case byte-compared against its
    staged ``hist_method=pallas`` twin at the SAME dtype policy: the
    flip's bit contract is that fused-vs-staged stays a pure scheduling
    change whatever the deep-dtype policy resolves to (the dtype leg
    itself is gated by the device AUC-parity capture, not bit parity —
    tools/precision_expt.py).  Then runs the required-guards gate
    (``ci_gate --require-guards``) over ``records_dir``'s newest BENCH
    record, so the flip cannot be declared rehearsed against a capture
    whose guards are not already green.  Returns the summary dict;
    ``ok`` is parity AND gate."""
    import ci_gate  # noqa: E402 — sibling tool, path set above
    import numpy as np

    import lightgbmv1_tpu as lgb

    rng = np.random.RandomState(11)
    X = rng.randn(900, 7)
    y_bin = (X[:, 0] - X[:, 1] + 0.4 * X[:, 2] > 0).astype(float)
    y_mc = np.clip((np.abs(X[:, 0]) + X[:, 1] > 1).astype(float)
                   + (X[:, 2] > 0.3), 0, 2)
    battery = {
        "binary": {"objective": "binary"},
        "multiclass": {"objective": "multiclass", "num_class": 3},
        "dart": {"objective": "binary", "boosting": "dart",
                 "drop_rate": 0.5},
        "wave_loop": {"objective": "binary", "wave_loop_rounds": 4},
        # sub-byte residency (ISSUE 18): the packed fused run's twin is
        # the staged UNPACKED run — the flip must hold across the layout
        # change, not just the scheduling change
        "packed4": {"objective": "binary", "max_bin": 15,
                    "bin_layout": "packed4"},
    }
    base = {"num_leaves": 31, "max_bin": 63, "min_data_in_leaf": 5,
            "verbosity": -1, "seed": 5, "tree_growth": "leafwise",
            "leafwise_wave_size": 8}

    def text(params, label):
        ds = lgb.Dataset(X, label=label, params=dict(params))
        booster = lgb.train(dict(params), ds, num_boost_round=int(iters),
                            verbose_eval=False)
        return booster.model_to_string()

    summary = {"flip": dict(FLIP_DEFAULTS), "parity": {}, "ok": False}
    parity_ok = True
    for name, over in battery.items():
        label = y_mc if name == "multiclass" else y_bin
        flip = text({**base, **over, **FLIP_DEFAULTS}, label)
        twin = {"hist_method": "pallas"}
        if name == "packed4":
            twin["bin_layout"] = "u8"
        staged = text({**base, **over, **FLIP_DEFAULTS, **twin}, label)
        same = bool(flip == staged)
        summary["parity"][name] = same
        parity_ok &= same
        out(f"flip-defaults: {name} parity "
            f"{'OK' if same else 'DIVERGED'}")

    # serving megakernel (ISSUE 19): the fused walk+accumulate predictor
    # over a packed-eligible model (max_bin 10 → every feature fits the
    # 16 nibble values), packed + unpacked twins both node-exact against
    # the HostTree oracle — so the next driver capture lands the device
    # legs with the parity half already rehearsed
    from lightgbmv1_tpu.models.predict import BatchPredictor

    pk_params = {**base, "objective": "binary", "max_bin": 10}
    ds_pk = lgb.Dataset(X, label=y_bin, params=dict(pk_params))
    bst_pk = lgb.train(dict(pk_params), ds_pk, num_boost_round=int(iters),
                       verbose_eval=False)
    trees_pk = bst_pk._all_trees()
    leaf_host = np.stack([t.predict_leaf_index(X) for t in trees_pk],
                         axis=1)
    for layout in ("packed4", "u8"):
        bp = BatchPredictor(trees_pk, 1, X.shape[1], method="fused",
                            code_layout=layout)
        same = bool(bp._fused_engaged()
                    and np.array_equal(bp.predict_leaf(X), leaf_host))
        summary["parity"][f"predict_fused_{layout}"] = same
        parity_ok &= same
        out(f"flip-defaults: predict_fused_{layout} parity "
            f"{'OK' if same else 'DIVERGED'}")
    summary["parity_ok"] = parity_ok

    gate_ok = ci_gate.check_required_guards(
        records_dir, ci_gate.REQUIRED_GUARDS, out=out)
    summary["guards_ok"] = bool(gate_ok)
    summary["ok"] = bool(parity_ok and gate_ok)
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--records-dir", default=ROOT,
                    help="where existing records live (round numbering + "
                         "trend priors)")
    ap.add_argument("--out", default=None,
                    help="capture artifact directory (default: a temp dir)")
    ap.add_argument("--round", type=int, default=None,
                    help="force the record round number")
    ap.add_argument("--dry-run", action="store_true",
                    help="rehearsal: records into a scratch dir, gated in "
                         "isolation; the repo's records are untouched")
    ap.add_argument("--t1-log", default="/tmp/_t1.log")
    ap.add_argument("--with-t1", action="store_true",
                    help="also enforce the tier-1 wall budget guard "
                         "(requires --t1-log from a suite run)")
    ap.add_argument("--window-rows", type=int, default=4096)
    ap.add_argument("--stage-timeout-s", type=float, default=7200.0)
    ap.add_argument("--flip-defaults", action="store_true",
                    help="rehearse ROADMAP item 1's default flip "
                         "(hist_method=fused + hist_dtype_deep=auto): "
                         "parity battery under the flipped defaults + "
                         "the required-guards gate; no records written")
    args = ap.parse_args(argv)
    if args.flip_defaults:
        summary = run_flip_rehearsal(records_dir=args.records_dir)
        print(json.dumps(summary, default=str))
        return 0 if summary["ok"] else 1
    summary = run_capture(
        records_dir=args.records_dir, out_dir=args.out,
        round_no=args.round, dry_run=args.dry_run,
        skip_t1=not args.with_t1, t1_log=args.t1_log,
        window_rows=args.window_rows,
        stage_timeout_s=args.stage_timeout_s)
    print(json.dumps(summary, default=str))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
