"""Tier-1 wall-budget guard (ROADMAP tier-1 verify runs under a hard
``timeout -k 10 870``; PR 6 measured ~863 s of that budget already
consumed, and a suite that creeps past the timeout is KILLED mid-run —
every test after the cut silently stops counting).

This tool turns that cliff into an explicit, rankable report:

    # during the tier-1 run, record per-test durations (tests/conftest.py)
    LGBMV1_T1_DURATIONS=/tmp/t1_durations.jsonl \
        python -m pytest tests/ -q -m 'not slow' ...

    # then project the wall against the budget (exit 1 over the bar)
    python tools/tier1_budget.py /tmp/t1_durations.jsonl

It also accepts a plain pytest log (the ``tee /tmp/_t1.log`` file the
verify command writes): the trailing ``in NNN.NNs`` wall is used, plus
any ``--durations`` section lines for offender ranking.

Exit status: 0 when projected wall <= ``frac * budget`` (default 95% of
870 s), 1 otherwise — wire it after the tier-1 run so budget creep fails
loudly BEFORE the driver's timeout starts eating tests.  The fix for a
failing guard is the PR-6 discipline: mark the listed offenders ``slow``
(they still run in the full suite / bench / driver captures) or shrink
documented-arbitrary scales at constant structure.

The demotion is meant to be REVERSIBLE: tests/conftest.py centralizes
the re-marks in ``_T1_REMARK_SLOW`` precisely so a faster box can bring
tests back by deleting entries.  ``--suggest-promote`` closes that loop:
given a FULL-suite durations log (``LGBMV1_T1_DURATIONS=... pytest
tests/ -q -m ''`` — a tier-1 log never executes the re-marked tests, so
it carries no durations for them), it projects the tier-1 wall without
the re-marked entries and greedily names the cheapest ones that fit
back under the bar, inflation-adjusted (in-suite wall historically runs
~15% over summed call durations; ``--inflate`` tunes the factor).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from collections import defaultdict

DEFAULT_BUDGET_S = 870.0     # ROADMAP tier-1 verify: timeout -k 10 870
DEFAULT_FRAC = 0.95

# pytest summary tail: "=== 337 passed, 3 failed, ... in 862.95s ... ==="
_WALL_RE = re.compile(r"\bin (\d+(?:\.\d+)?)s\b")
# pytest --durations section: "12.34s call     tests/test_x.py::test_y"
_DUR_LINE_RE = re.compile(
    r"^\s*(\d+(?:\.\d+)?)s\s+(call|setup|teardown)\s+(\S+)")


def parse_durations_jsonl(lines):
    """Per-test totals + wall projection from the conftest JSONL records.
    Returns ``(per_test dict, projected_wall_s)`` — the projection is the
    sum of every recorded phase (collection/import overhead rides inside
    the first tests' setup phases, so the sum tracks the measured wall
    within a few percent)."""
    per_test = defaultdict(float)
    total = 0.0
    for line in lines:
        line = line.strip()
        if not line:
            continue
        rec = json.loads(line)
        d = float(rec.get("duration", 0.0))
        per_test[rec["nodeid"]] += d
        total += d
    return dict(per_test), total


def parse_pytest_log(lines):
    """``(per_test dict, wall_s or None)`` from a pytest console log."""
    per_test = defaultdict(float)
    wall = None
    for line in lines:
        m = _DUR_LINE_RE.match(line)
        if m:
            per_test[m.group(3)] += float(m.group(1))
        m = _WALL_RE.search(line)
        if m:
            wall = float(m.group(1))   # keep the LAST (summary) match
    return dict(per_test), wall


def load(path):
    with open(path) as fh:
        first = fh.readline()
        rest = fh.readlines()
    lines = [first] + rest
    try:
        json.loads(first)
        is_jsonl = True
    except (ValueError, TypeError):
        is_jsonl = False
    if is_jsonl:
        return parse_durations_jsonl(lines)
    return parse_pytest_log(lines)


def report(per_test, wall, budget=DEFAULT_BUDGET_S, frac=DEFAULT_FRAC,
           top=15, out=print):
    """Render the budget report; returns True when within budget."""
    bar = frac * budget
    ok = wall is not None and wall <= bar
    out(f"tier-1 projected wall: "
        + (f"{wall:.1f} s" if wall is not None else "UNKNOWN")
        + f" of {budget:.0f} s budget (bar = {frac:.0%} = {bar:.1f} s)"
        + f" -> {'OK' if ok else 'OVER BUDGET'}")
    if per_test:
        worst = sorted(per_test.items(), key=lambda kv: -kv[1])[:top]
        out(f"worst {len(worst)} offenders (candidates for the `slow` "
            "mark — still run by the full suite and driver captures):")
        for nodeid, d in worst:
            out(f"  {d:8.2f}s  {nodeid}")
    if not ok and wall is not None:
        out(f"over by {wall - bar:.1f} s: mark offenders `slow` or shrink "
            "documented-arbitrary test scales at constant structure")
    return ok


def load_remark_table(conftest_path=None):
    """The ``_T1_REMARK_SLOW`` entries from tests/conftest.py, parsed
    out of the SOURCE (importing conftest would set JAX env vars and
    drag the whole runtime into a bookkeeping tool)."""
    import ast

    if conftest_path is None:
        conftest_path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tests", "conftest.py")
    with open(conftest_path) as fh:
        tree = ast.parse(fh.read())
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and getattr(node.targets[0], "id", "") == "_T1_REMARK_SLOW"):
            # the value is ``frozenset((<string literals>))`` — the call
            # itself is not a literal, its tuple argument is
            return frozenset(ast.literal_eval(node.value.args[0]))
    raise ValueError(f"_T1_REMARK_SLOW not found in {conftest_path}")


DEFAULT_INFLATE = 1.15   # conftest-measured in-suite wall over summed calls


def suggest_promote(per_test, budget=DEFAULT_BUDGET_S, frac=DEFAULT_FRAC,
                    inflate=DEFAULT_INFLATE, conftest_path=None, out=print):
    """Name the ``_T1_REMARK_SLOW`` entries that fit back under the bar.

    Wants a FULL-suite durations log (``-m ''``): the tier-1 base is the
    sum over tests NOT in the re-mark table, and candidates are packed
    cheapest-first into ``bar - inflate * base``.  Returns the list of
    ``(nodeid, duration_s)`` picks."""
    bar = frac * budget
    table = load_remark_table(conftest_path)
    durs = defaultdict(float)
    for nodeid, d in per_test.items():
        key = nodeid[len("tests/"):] if nodeid.startswith("tests/") else nodeid
        durs[key] += d
    marked = {k: durs[k] for k in table if k in durs}
    unknown = sorted(k for k in table if k not in durs)
    base = sum(d for k, d in durs.items() if k not in table)
    headroom = bar - inflate * base
    out(f"tier-1 base projection without the {len(table)} re-marked slow "
        f"entries: {base:.1f} s (x{inflate:.2f} in-suite inflation = "
        f"{base * inflate:.1f} s) vs bar {bar:.1f} s -> headroom "
        f"{headroom:.1f} s")
    picks = []
    for k, d in sorted(marked.items(), key=lambda kv: (kv[1], kv[0])):
        cost = d * inflate
        if cost <= headroom:
            picks.append((k, d))
            headroom -= cost
    if picks:
        out(f"promote candidates — {len(picks)} of {len(marked)} measured "
            "entries fit; DELETE these from tests/conftest.py "
            "_T1_REMARK_SLOW to re-promote:")
        for k, d in picks:
            out(f"  {d:8.2f}s  {k}")
    else:
        out("no measured re-marked entry fits back under the bar")
    if unknown:
        out(f"{len(unknown)} re-marked entries carry no duration in this "
            "log (a tier-1 `-m 'not slow'` run never executes them) — "
            "measure with the full suite: LGBMV1_T1_DURATIONS=... "
            "python -m pytest tests/ -q -m ''")
    return picks


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", nargs="?", default="/tmp/_t1.log",
                    help="durations JSONL (tests/conftest.py) or pytest log")
    ap.add_argument("--budget", type=float, default=DEFAULT_BUDGET_S)
    ap.add_argument("--frac", type=float, default=DEFAULT_FRAC)
    ap.add_argument("--top", type=int, default=15)
    ap.add_argument("--suggest-promote", action="store_true",
                    help="advisory mode: from a FULL-suite durations log, "
                         "name _T1_REMARK_SLOW entries that fit back under "
                         "the bar (exit 0 regardless of the budget check)")
    ap.add_argument("--inflate", type=float, default=DEFAULT_INFLATE,
                    help="wall-over-summed-durations safety factor applied "
                         "to the base projection and each candidate")
    ap.add_argument("--conftest", default=None,
                    help="override the tests/conftest.py to read the "
                         "re-mark table from")
    args = ap.parse_args(argv)
    per_test, wall = load(args.path)
    if args.suggest_promote:
        suggest_promote(per_test, budget=args.budget, frac=args.frac,
                        inflate=args.inflate, conftest_path=args.conftest)
        return 0
    ok = report(per_test, wall, budget=args.budget, frac=args.frac,
                top=args.top)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
