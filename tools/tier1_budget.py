"""Tier-1 wall-budget guard (ROADMAP tier-1 verify runs under a hard
``timeout -k 10 870``; PR 6 measured ~863 s of that budget already
consumed, and a suite that creeps past the timeout is KILLED mid-run —
every test after the cut silently stops counting).

This tool turns that cliff into an explicit, rankable report:

    # during the tier-1 run, record per-test durations (tests/conftest.py)
    LGBMV1_T1_DURATIONS=/tmp/t1_durations.jsonl \
        python -m pytest tests/ -q -m 'not slow' ...

    # then project the wall against the budget (exit 1 over the bar)
    python tools/tier1_budget.py /tmp/t1_durations.jsonl

It also accepts a plain pytest log (the ``tee /tmp/_t1.log`` file the
verify command writes): the trailing ``in NNN.NNs`` wall is used, plus
any ``--durations`` section lines for offender ranking.

Exit status: 0 when projected wall <= ``frac * budget`` (default 95% of
870 s), 1 otherwise — wire it after the tier-1 run so budget creep fails
loudly BEFORE the driver's timeout starts eating tests.  The fix for a
failing guard is the PR-6 discipline: mark the listed offenders ``slow``
(they still run in the full suite / bench / driver captures) or shrink
documented-arbitrary scales at constant structure.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from collections import defaultdict

DEFAULT_BUDGET_S = 870.0     # ROADMAP tier-1 verify: timeout -k 10 870
DEFAULT_FRAC = 0.95

# pytest summary tail: "=== 337 passed, 3 failed, ... in 862.95s ... ==="
_WALL_RE = re.compile(r"\bin (\d+(?:\.\d+)?)s\b")
# pytest --durations section: "12.34s call     tests/test_x.py::test_y"
_DUR_LINE_RE = re.compile(
    r"^\s*(\d+(?:\.\d+)?)s\s+(call|setup|teardown)\s+(\S+)")


def parse_durations_jsonl(lines):
    """Per-test totals + wall projection from the conftest JSONL records.
    Returns ``(per_test dict, projected_wall_s)`` — the projection is the
    sum of every recorded phase (collection/import overhead rides inside
    the first tests' setup phases, so the sum tracks the measured wall
    within a few percent)."""
    per_test = defaultdict(float)
    total = 0.0
    for line in lines:
        line = line.strip()
        if not line:
            continue
        rec = json.loads(line)
        d = float(rec.get("duration", 0.0))
        per_test[rec["nodeid"]] += d
        total += d
    return dict(per_test), total


def parse_pytest_log(lines):
    """``(per_test dict, wall_s or None)`` from a pytest console log."""
    per_test = defaultdict(float)
    wall = None
    for line in lines:
        m = _DUR_LINE_RE.match(line)
        if m:
            per_test[m.group(3)] += float(m.group(1))
        m = _WALL_RE.search(line)
        if m:
            wall = float(m.group(1))   # keep the LAST (summary) match
    return dict(per_test), wall


def load(path):
    with open(path) as fh:
        first = fh.readline()
        rest = fh.readlines()
    lines = [first] + rest
    try:
        json.loads(first)
        is_jsonl = True
    except (ValueError, TypeError):
        is_jsonl = False
    if is_jsonl:
        return parse_durations_jsonl(lines)
    return parse_pytest_log(lines)


def report(per_test, wall, budget=DEFAULT_BUDGET_S, frac=DEFAULT_FRAC,
           top=15, out=print):
    """Render the budget report; returns True when within budget."""
    bar = frac * budget
    ok = wall is not None and wall <= bar
    out(f"tier-1 projected wall: "
        + (f"{wall:.1f} s" if wall is not None else "UNKNOWN")
        + f" of {budget:.0f} s budget (bar = {frac:.0%} = {bar:.1f} s)"
        + f" -> {'OK' if ok else 'OVER BUDGET'}")
    if per_test:
        worst = sorted(per_test.items(), key=lambda kv: -kv[1])[:top]
        out(f"worst {len(worst)} offenders (candidates for the `slow` "
            "mark — still run by the full suite and driver captures):")
        for nodeid, d in worst:
            out(f"  {d:8.2f}s  {nodeid}")
    if not ok and wall is not None:
        out(f"over by {wall - bar:.1f} s: mark offenders `slow` or shrink "
            "documented-arbitrary test scales at constant structure")
    return ok


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", nargs="?", default="/tmp/_t1.log",
                    help="durations JSONL (tests/conftest.py) or pytest log")
    ap.add_argument("--budget", type=float, default=DEFAULT_BUDGET_S)
    ap.add_argument("--frac", type=float, default=DEFAULT_FRAC)
    ap.add_argument("--top", type=int, default=15)
    args = ap.parse_args(argv)
    per_test, wall = load(args.path)
    ok = report(per_test, wall, budget=args.budget, frac=args.frac,
                top=args.top)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
