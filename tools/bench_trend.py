"""Bench regression sentinel: the BENCH_r*/MULTICHIP_r* trajectory gate.

The repo's standing discipline puts every device-sensitive claim into a
captured record with an ``*_ok`` guard — but until ISSUE 9 nothing read
the records AS A SERIES: a capture could quietly regress a headline
number (or flip a guard that a previous round held green) and the only
defense was a reviewer's memory.  This tool is the missing comparator:

* loads every ``BENCH_r*.json`` (the ``parsed`` block) and every
  ``MULTICHIP_r*.json`` (the ``dryrun_multichip PARITY {...}`` JSON in
  the captured tail, when a round carries one — the same extraction
  tools/perf_report.py uses);
* builds the per-field trajectory and judges the NEWEST record:
  - any watched ms/throughput/quality field more than its tolerance
    (default 10%) WORSE than the best prior record -> regression;
  - any boolean ``*_ok`` / ``*parity*`` guard that is False in the
    newest record -> flagged (a ``guard_flip`` when the latest prior
    record carrying the field had it True, ``guard_false`` otherwise);
* exits non-zero when anything is flagged, so a driver capture can be
  gated on it (tools/ci_gate.py wires it next to the tier-1 budget
  guard), and renders the trend rows tools/perf_report.py turns into
  PERF.md's "Trend" section.

Watched fields are a CURATED list, not a regex sweep: several recorded
ms fields are methodology-coupled (e.g. ``hist_ms_per_iter`` re-prices
the replayed schedule each round; the r04->r05 roofline denominator
drift is a documented tunnel artifact), and a sentinel that cries wolf
on those gets disabled within two rounds.  Each entry names its
direction and tolerance; quality fields get tight tolerances, clocked
fields get the 10% bar the acceptance criteria name.

Usage:

    python tools/bench_trend.py                 # repo records, exit 0/1
    python tools/bench_trend.py --dir /tmp/recs # any record directory
    python tools/bench_trend.py --json          # machine-readable report
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (field, direction, relative tolerance).  direction "up": bigger is
# better (throughput/quality); "down": smaller is better (clocks).
WATCHED: Tuple[Tuple[str, str, float], ...] = (
    ("value", "up", 0.10),
    ("vs_baseline", "up", 0.10),
    ("vs_ref_same_host", "up", 0.10),
    ("vs_ref_500iter", "up", 0.10),
    ("auc", "up", 0.005),
    ("tpu_500iter_auc", "up", 0.005),
    ("tpu_500iter_wall_s", "down", 0.10),
    ("hist_ms_per_pass", "down", 0.10),
    ("hist_ms_per_pass_deep", "down", 0.10),
    ("levelwise_M_row_trees_per_s", "up", 0.10),
    ("dart_M_row_trees_per_s", "up", 0.10),
    ("multiclass_M_row_trees_per_s", "up", 0.10),
    ("rank_M_row_trees_per_s", "up", 0.10),
    ("multiclass_logloss", "down", 0.02),
    ("rank_ndcg10", "up", 0.005),
    ("predict_M_rows_per_s", "up", 0.10),
    ("predict_device_compute_M_rows_per_s", "up", 0.10),
    # serving megakernel (ISSUE 19): the fused walk+accumulate rate and
    # the 4-bit packed serving-code transport — analytic ceil(F/2)
    # bytes/row, so ANY upward move means packing stopped engaging at
    # the bench twin; predict_fused_ok is the boolean guard beside them
    ("predict_fused_M_rows_per_s", "up", 0.10),
    ("predict_h2d_bytes_per_row_packed", "down", 0.10),
    ("serve_qps", "up", 0.10),
    ("serve_p99_ms", "down", 0.10),
    # multi-tenant serving (ISSUE 20): the shared-jit-cache hit rate —
    # ANY downward move means tenants stopped adopting each other's
    # executables — and the noisy-neighbor p99 tax on the cold tenant
    # under the hot-tenant overload probe (CPU-thread-scheduling noisy,
    # so the bar is loose); tenant_ok is the boolean guard beside them
    ("tenant_compile_share_frac", "up", 0.10),
    ("tenant_isolation_p99_delta_ms", "down", 0.50),
    ("stream_ms_per_iter", "down", 0.10),
    ("pipeline_ms_per_iter", "down", 0.10),
    ("obs_overhead_frac", "down", 0.10),
    # forensics & SLO (ISSUE 10): the availability SLI is a quality
    # field (tight bar); slo_ok / forensics_ok / obs_agg_ok /
    # chaos_forensics_ok are booleans — the guard sweep below flags any
    # False automatically
    ("slo_availability", "up", 0.005),
    # fault-tolerant fleet (ISSUE 11): the elastic re-bootstrap clock is
    # lease-timeout-dominated, so the bar is loose; fleet_ok /
    # chaos_fleet_ok / the *_ok sub-guards are booleans the guard sweep
    # flags automatically
    ("fleet_recovery_s", "down", 0.50),
    # device truth (ISSUE 12): compile time is noisy (cache state, load,
    # whole-process cumulative) — generous bar, watched so a retrace
    # storm or a compile-time explosion is still a flagged number; the
    # HBM footprint gets the standard 10% bar so the Pallas-megakernel
    # work of ROADMAP item 2 lands against a baseline
    ("compile_ms_total", "down", 0.50),
    ("hbm_peak_bytes", "down", 0.10),
    # fused wave-round megakernel (ISSUE 13): the merged hist+split
    # round priced over the replayed schedule gets the standard 10%
    # clock bar; fused_ok / fused_parity_ok are booleans the guard
    # sweep flags automatically
    ("hist_split_fused_ms_per_iter", "down", 0.10),
    # single-pass wave round (ISSUE 15): the routed round — partition +
    # valid routing + top-k folded into the fused dispatch — gets the
    # same 10% clock bar; fused_round_ok is the boolean guard the sweep
    # flags automatically
    ("partition_fused_ms_per_iter", "down", 0.10),
    # persistent multi-round wave loop (ISSUE 17): the looped dispatch
    # priced by the differential method (single-round dispatch ms minus
    # the measured boundary saving) at the standard 10% bar — a
    # regression here means the loop stopped paying for its resident
    # state; fused_loop_ok / fused_loop_parity_ok are booleans the
    # guard sweep flags automatically
    ("phase_wave_loop_ms", "down", 0.10),
    # sub-byte bin residency (ISSUE 18): the per-round packed binned
    # read in bytes — analytic ceil(F/2) * N, so ANY upward move means
    # the packed layout stopped engaging at the bench config; packed_ok
    # / packed_parity_ok are booleans the guard sweep flags
    # automatically
    ("packed_binned_bytes", "down", 0.10),
    # model-quality & drift (ISSUE 14): the skew-injection probe's
    # detection magnitude is deterministic (same shift, same shape) —
    # a capture where the injected PSI collapses means the detector
    # lost power.  drift_overhead_frac is deliberately NOT watched
    # (sub-noise-floor fraction; the drift_ok guard already enforces
    # the <= 2% contract), like the other methodology-coupled fields.
    ("drift_injected_psi", "up", 0.25),
    # pod-scale two-level collective (ISSUE 16): the DCN (slow inter-
    # host link) histogram wire bytes per round, flat-scalar mirror of
    # hier_comm_bytes_per_round["data"]["dcn"]["hist_bytes"], at the
    # standard 10% bar — a regression here means the slow link started
    # carrying more than the 1/C chip slice; hier_comm_ok is the
    # boolean guard the sweep flags automatically
    ("hier_dcn_hist_bytes", "down", 0.10),
)

_PARITY_RE = re.compile(r"dryrun_multichip PARITY (\{.*\})")


def _is_guard_field(name: str, value) -> bool:
    return isinstance(value, bool) and (name.endswith("_ok")
                                        or "parity" in name)


def load_bench_records(root: str) -> List[Tuple[str, Dict]]:
    """``[(name, parsed record)]`` sorted by round."""
    out = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        try:
            with open(path) as fh:
                rec = json.load(fh)
        except ValueError:
            continue
        parsed = rec.get("parsed", rec)
        if isinstance(parsed, dict) and parsed:
            out.append((os.path.basename(path), parsed))
    return out


def load_multichip_records(root: str) -> List[Tuple[str, Dict]]:
    """``[(name, PARITY record)]`` for captures whose tail carries one
    (older rounds were liveness-only and contribute nothing)."""
    out = []
    for path in sorted(glob.glob(os.path.join(root, "MULTICHIP_r*.json"))):
        try:
            with open(path) as fh:
                rec = json.load(fh)
        except ValueError:
            continue
        m = _PARITY_RE.search(rec.get("tail", "") or "")
        if not m:
            continue
        try:
            out.append((os.path.basename(path), json.loads(m.group(1))))
        except ValueError:
            continue
    return out


def _best_prior(records: List[Tuple[str, Dict]], field: str,
                direction: str) -> Optional[Tuple[str, float]]:
    best = None
    for name, rec in records[:-1]:
        v = rec.get(field)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        if best is None or (direction == "up" and v > best[1]) \
                or (direction == "down" and v < best[1]):
            best = (name, float(v))
    return best


def check_series(records: List[Tuple[str, Dict]],
                 watched=WATCHED) -> Tuple[List[Dict], List[Dict]]:
    """Judge the newest record of one series; returns
    ``(flags, trend_rows)``.  ``trend_rows`` carries every watched field
    present in the newest record (for the PERF.md "Trend" table);
    ``flags`` the regressions/guard failures."""
    flags: List[Dict] = []
    rows: List[Dict] = []
    if not records:
        return flags, rows
    newest_name, newest = records[-1]
    for field, direction, tol in watched:
        cur = newest.get(field)
        if not isinstance(cur, (int, float)) or isinstance(cur, bool):
            continue
        best = _best_prior(records, field, direction)
        row = {"field": field, "direction": direction, "tol": tol,
               "current": float(cur), "record": newest_name,
               "best_prior": best[1] if best else None,
               "best_prior_record": best[0] if best else None,
               "regressed": False}
        if best is not None and best[1] > 0:
            if direction == "up":
                regressed = cur < best[1] * (1.0 - tol)
            else:
                regressed = cur > best[1] * (1.0 + tol)
            if regressed:
                row["regressed"] = True
                flags.append({
                    "kind": "regression", "field": field,
                    "record": newest_name, "current": float(cur),
                    "best_prior": best[1], "best_prior_record": best[0],
                    "direction": direction, "tol": tol,
                })
        rows.append(row)
    # guard flips: every boolean *_ok / *parity* field of the newest
    # record that reads False fails the gate; "flip" when the latest
    # prior record carrying the field held it True
    for field, val in sorted(newest.items()):
        if not _is_guard_field(field, val) or val:
            continue
        prior = None
        for name, rec in reversed(records[:-1]):
            if field in rec and isinstance(rec[field], bool):
                prior = (name, rec[field])
                break
        flags.append({
            "kind": ("guard_flip" if prior and prior[1] else "guard_false"),
            "field": field, "record": newest_name,
            "prior_record": prior[0] if prior else None,
        })
    return flags, rows


def run(root: str = ROOT, watched=WATCHED) -> Dict:
    """The full sentinel pass over a record directory."""
    bench = load_bench_records(root)
    multichip = load_multichip_records(root)
    b_flags, b_rows = check_series(bench, watched)
    m_flags, m_rows = check_series(multichip, watched)
    return {
        "bench_records": [n for n, _ in bench],
        "multichip_records": [n for n, _ in multichip],
        "flags": b_flags + m_flags,
        "trend_rows": b_rows + m_rows,
        "ok": not (b_flags + m_flags),
    }


def render_report(result: Dict, out=print) -> None:
    names = result["bench_records"]
    out(f"bench_trend: {len(names)} BENCH record(s) "
        f"({names[0] if names else '—'} .. {names[-1] if names else '—'}), "
        f"{len(result['multichip_records'])} MULTICHIP PARITY record(s)")
    for row in result["trend_rows"]:
        if row["best_prior"] is None:
            note = "first capture of this field"
        else:
            arrow = {"up": ">=", "down": "<="}[row["direction"]]
            note = (f"best prior {row['best_prior']:g} "
                    f"({row['best_prior_record']}), bar: {arrow} "
                    f"{(1 - row['tol']) if row['direction'] == 'up' else (1 + row['tol']):g}x")
        mark = "REGRESSED" if row["regressed"] else "ok"
        out(f"  [{mark:>9}] {row['field']} = {row['current']:g} — {note}")
    for f in result["flags"]:
        if f["kind"] == "regression":
            out(f"  FLAG regression: {f['field']} {f['current']:g} vs best "
                f"prior {f['best_prior']:g} ({f['best_prior_record']}) "
                f"beyond {f['tol']:.0%}")
        else:
            out(f"  FLAG {f['kind']}: {f['field']} is False in "
                f"{f['record']}"
                + (f" (was True in {f['prior_record']})"
                   if f.get("prior_record") else ""))
    out(f"bench_trend: {'OK' if result['ok'] else 'REGRESSIONS FLAGGED'}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=ROOT,
                    help="directory holding BENCH_r*/MULTICHIP_r* records")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable report")
    args = ap.parse_args(argv)
    result = run(args.dir)
    if args.json:
        print(json.dumps(result, indent=1))
    else:
        render_report(result)
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
