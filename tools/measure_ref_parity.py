"""Measure the reference C++ CLI on the bench's multiclass / lambdarank
parity datasets (VERDICT r4 missing #1) — run on an IDLE host, 1 core.

Generates the IDENTICAL synthetic data bench.py uses (same generator
functions, same seeds), writes TSVs + .query files, runs the reference
binary (built at /tmp/refbuild/lightgbm per the round-4 recipe:
`cmake -S /root/reference -B /tmp/refbuild && move artifacts out of the
source dir`), and prints the constants to record in bench.py
(REF_MC_* / REF_RK_*).

Training-only timing: process wall minus the binary's logged data-loading
time, with metric_freq = num_iterations so per-iteration eval cost is
excluded (the same discipline as the binary-objective yardstick recorded
in round 4)."""
import os
import re
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import make_multiclass_data, make_rank_data  # noqa: E402

BIN = os.environ.get("REF_LGBM", "/tmp/refbuild/lightgbm")
WORK = "/tmp/ref_parity"
os.makedirs(WORK, exist_ok=True)


def write_tsv(path, X, y):
    t0 = time.time()
    arr = np.column_stack([y, X])
    np.savetxt(path, arr, fmt="%.6g", delimiter="\t")
    print(f"wrote {path} in {time.time() - t0:.1f}s", flush=True)


def run_conf(name, lines):
    conf = os.path.join(WORK, f"{name}.conf")
    with open(conf, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    t0 = time.time()
    out = subprocess.run([BIN, f"config={conf}"], cwd=WORK,
                         capture_output=True, text=True, timeout=3600)
    wall = time.time() - t0
    text = out.stdout + out.stderr
    m = re.search(r"Finished loading data in ([\d.]+) seconds", text)
    load_s = float(m.group(1)) if m else 0.0
    return wall - load_s, text


def main():
    # ---- multiclass (must mirror bench.py's cfg_mc block) ----------------
    MC_N, MC_CLS, MC_IT = 250_000, 5, 50
    Xm, ym = make_multiclass_data(MC_N, 10, MC_CLS)
    Xmv, ymv = make_multiclass_data(50_000, 11, MC_CLS)
    tr, va = os.path.join(WORK, "mc.train.tsv"), os.path.join(WORK, "mc.valid.tsv")
    if not os.path.exists(tr):
        write_tsv(tr, Xm, ym)
        write_tsv(va, Xmv, ymv)
    train_s, text = run_conf("mc", [
        "task = train", "objective = multiclass", f"num_class = {MC_CLS}",
        f"data = {tr}", f"valid = {va}", "num_leaves = 127", "max_bin = 63",
        "learning_rate = 0.1", "min_data_in_leaf = 20",
        "metric = multi_logloss", f"num_iterations = {MC_IT}",
        f"metric_freq = {MC_IT}", "num_threads = 1", "verbosity = 1",
        "output_model = /dev/null",
    ])
    lls = re.findall(r"multi_logloss\s*:\s*([\d.]+)", text)
    mrt = MC_N * MC_IT * MC_CLS / train_s / 1e6
    print(f"REF_MC_M_ROW_TREES_S = {mrt:.3f}   # {train_s:.1f}s train")
    print(f"REF_MC_LOGLOSS = {lls[-1] if lls else None}")

    # ---- lambdarank (must mirror bench.py's cfg_rk block) ----------------
    RK_Q, RK_D, RK_IT = 2000, 100, 100
    Xr, yr, gr = make_rank_data(RK_Q, RK_D, 20)
    Xrv, yrv, grv = make_rank_data(400, RK_D, 21)
    tr, va = os.path.join(WORK, "rk.train.tsv"), os.path.join(WORK, "rk.valid.tsv")
    if not os.path.exists(tr):
        write_tsv(tr, Xr, yr)
        write_tsv(va, Xrv, yrv)
        np.savetxt(tr + ".query", gr, fmt="%d")
        np.savetxt(va + ".query", grv, fmt="%d")
    train_s, text = run_conf("rk", [
        "task = train", "objective = lambdarank",
        f"data = {tr}", f"valid = {va}", "num_leaves = 63", "max_bin = 63",
        "learning_rate = 0.1", "min_data_in_leaf = 20",
        "metric = ndcg", "eval_at = 10", f"num_iterations = {RK_IT}",
        f"metric_freq = {RK_IT}", "num_threads = 1", "verbosity = 1",
        "output_model = /dev/null",
    ])
    nd = re.findall(r"ndcg@10\s*:\s*([\d.]+)", text)
    mrt = RK_Q * RK_D * RK_IT / train_s / 1e6
    print(f"REF_RK_M_ROW_TREES_S = {mrt:.3f}   # {train_s:.1f}s train")
    print(f"REF_RK_NDCG10 = {nd[-1] if nd else None}")


if __name__ == "__main__":
    main()
