"""Measure the reference C++ CLI on the bench's multiclass / lambdarank
parity datasets (VERDICT r4 missing #1) — run on an IDLE host, 1 core.

Generates the IDENTICAL synthetic data bench.py uses (same generator
functions, same seeds), writes TSVs + .query files, runs the reference
binary (built at /tmp/refbuild/lightgbm per the round-4 recipe:
`cmake -S /root/reference -B /tmp/refbuild && move artifacts out of the
source dir`), and prints the constants to record in bench.py
(REF_MC_* / REF_RK_*).

Training-only timing: process wall minus the binary's logged data-loading
time, with metric_freq = num_iterations so per-iteration eval cost is
excluded (the same discipline as the binary-objective yardstick recorded
in round 4)."""
import os
import re
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import make_data, make_multiclass_data, make_rank_data  # noqa: E402

BIN = os.environ.get("REF_LGBM", "/tmp/refbuild/lightgbm")
WORK = "/tmp/ref_parity"
os.makedirs(WORK, exist_ok=True)


def write_tsv(path, X, y):
    t0 = time.time()
    arr = np.column_stack([y, X])
    np.savetxt(path, arr, fmt="%.6g", delimiter="\t")
    print(f"wrote {path} in {time.time() - t0:.1f}s", flush=True)


def run_conf(name, lines):
    conf = os.path.join(WORK, f"{name}.conf")
    with open(conf, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    t0 = time.time()
    out = subprocess.run([BIN, f"config={conf}"], cwd=WORK,
                         capture_output=True, text=True, timeout=3600)
    wall = time.time() - t0
    text = out.stdout + out.stderr
    m = re.search(r"Finished loading data in ([\d.]+) seconds", text)
    load_s = float(m.group(1)) if m else 0.0
    return wall - load_s, text


def main():
    # ---- multiclass (must mirror bench.py's cfg_mc block) ----------------
    MC_N, MC_CLS, MC_IT = 250_000, 5, 50
    Xm, ym = make_multiclass_data(MC_N, 10, MC_CLS)
    Xmv, ymv = make_multiclass_data(50_000, 11, MC_CLS)
    tr, va = os.path.join(WORK, "mc.train.tsv"), os.path.join(WORK, "mc.valid.tsv")
    if not os.path.exists(tr):
        write_tsv(tr, Xm, ym)
        write_tsv(va, Xmv, ymv)
    train_s, text = run_conf("mc", [
        "task = train", "objective = multiclass", f"num_class = {MC_CLS}",
        f"data = {tr}", f"valid = {va}", "num_leaves = 127", "max_bin = 63",
        "learning_rate = 0.1", "min_data_in_leaf = 20",
        "metric = multi_logloss", f"num_iterations = {MC_IT}",
        f"metric_freq = {MC_IT}", "num_threads = 1", "verbosity = 1",
        "output_model = /dev/null",
    ])
    lls = re.findall(r"multi_logloss\s*:\s*([\d.]+)", text)
    mrt = MC_N * MC_IT * MC_CLS / train_s / 1e6
    print(f"REF_MC_M_ROW_TREES_S = {mrt:.3f}   # {train_s:.1f}s train")
    print(f"REF_MC_LOGLOSS = {lls[-1] if lls else None}")

    # ---- lambdarank (must mirror bench.py's cfg_rk block) ----------------
    RK_Q, RK_D, RK_IT = 2000, 100, 100
    Xr, yr, gr = make_rank_data(RK_Q, RK_D, 20)
    Xrv, yrv, grv = make_rank_data(400, RK_D, 21)
    tr, va = os.path.join(WORK, "rk.train.tsv"), os.path.join(WORK, "rk.valid.tsv")
    if not os.path.exists(tr):
        write_tsv(tr, Xr, yr)
        write_tsv(va, Xrv, yrv)
        np.savetxt(tr + ".query", gr, fmt="%d")
        np.savetxt(va + ".query", grv, fmt="%d")
    train_s, text = run_conf("rk", [
        "task = train", "objective = lambdarank",
        f"data = {tr}", f"valid = {va}", "num_leaves = 63", "max_bin = 63",
        "learning_rate = 0.1", "min_data_in_leaf = 20",
        "metric = ndcg", "eval_at = 10", f"num_iterations = {RK_IT}",
        f"metric_freq = {RK_IT}", "num_threads = 1", "verbosity = 1",
        "output_model = /dev/null",
    ])
    nd = re.findall(r"ndcg@10\s*:\s*([\d.]+)", text)
    mrt = RK_Q * RK_D * RK_IT / train_s / 1e6
    print(f"REF_RK_M_ROW_TREES_S = {mrt:.3f}   # {train_s:.1f}s train")
    print(f"REF_RK_NDCG10 = {nd[-1] if nd else None}")

    # ---- prediction (must mirror bench.py's measure_predict block) -------
    # reference CLI task=predict, file->file, on the 1M-row binary bench
    # set with a 100-tree model trained at the bench config (VERDICT r5
    # #6).  Prediction wall is PROCESS wall: the CLI's parse + predict +
    # result write is exactly what bench.py times for our engines.
    PR_N, PR_IT = 1_000_000, 100
    Xb, yb = make_data(PR_N, 0)
    tr = os.path.join(WORK, "bin.train.tsv")
    if not os.path.exists(tr):
        write_tsv(tr, Xb, yb)
    model = os.path.join(WORK, "bin.model.txt")
    train_s, _ = run_conf("bin_train", [
        "task = train", "objective = binary", f"data = {tr}",
        "num_leaves = 255", "max_bin = 63", "learning_rate = 0.1",
        "min_data_in_leaf = 20", f"num_iterations = {PR_IT}",
        f"metric_freq = {PR_IT}", "num_threads = 1", "verbosity = 1",
        f"output_model = {model}",
    ])
    t0 = time.time()
    out = subprocess.run([BIN, "task=predict",
                          f"data={tr}", f"input_model={model}",
                          f"output_result={os.path.join(WORK, 'bin.pred')}",
                          "num_threads=1", "verbosity=1"],
                         cwd=WORK, capture_output=True, text=True,
                         timeout=3600)
    wall = time.time() - t0
    print(f"REF_PREDICT_M_ROWS_S = {PR_N / wall / 1e6:.3f}"
          f"   # {wall:.1f}s file->file, {PR_IT} trees"
          + ("" if out.returncode == 0 else "  [predict rc != 0 — CHECK]"))


if __name__ == "__main__":
    main()
