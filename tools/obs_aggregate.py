"""Merge per-process obs artifacts into ONE Perfetto trace + snapshot.

A fleet run leaves one artifact set per process — ``<label>.trace.json``
/ ``<label>.metrics.json`` / ``<label>.events.jsonl`` written by
``obs.agg.export_process_artifacts`` (the CLI writes them when
``obs_dir=<dir>`` or ``LGBMV1_OBS_DIR`` is set), plus ``crash-*.zip``
forensic bundles from any process that died (obs/dump.py).  This tool
merges everything in a directory into:

* ``merged.trace.json`` — one Chrome trace: each process is a distinct
  pid lane named ``role host:pid``, rebased onto a shared wall-clock
  axis (open at https://ui.perfetto.dev);
* ``merged.metrics.json`` — per-process snapshots verbatim plus an
  additive ``merged`` view (``*_total``/``*_count``/``*_sum`` summed,
  ``*_max`` maxed) and the interleaved cross-process event log.

Usage::

    python tools/obs_aggregate.py <artifact_dir>
        [--out merged.trace.json] [--metrics-out merged.metrics.json]
        [--profile-dir DIR] [--json]

``--profile-dir`` ingests a ``jax.profiler`` capture (the device lane:
``profile_dir=`` CLI knob or ``tools/capture.py``) next to the host
lanes, wall-clock-anchored by its ``profile.anchor.json`` sidecar, and
reconciles estimated host phase spans against the measured device rows
(per-phase agreement ratio in the merged trace's ``otherData``).

Exit 0 with a one-line summary (or the full JSON summary under
``--json``); exit 1 when the directory holds no artifacts at all.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from lightgbmv1_tpu.obs import agg  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("artifact_dir",
                    help="directory of per-process obs artifacts "
                         "(and/or crash-*.zip forensic bundles)")
    ap.add_argument("--out", default=None,
                    help="merged Chrome trace path "
                         "(default <dir>/merged.trace.json)")
    ap.add_argument("--metrics-out", default=None,
                    help="merged metrics path "
                         "(default <dir>/merged.metrics.json)")
    ap.add_argument("--profile-dir", default=None,
                    help="jax.profiler capture directory to merge as the "
                         "device lane (profile.anchor.json aligns it)")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable summary")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.artifact_dir):
        print(f"obs_aggregate: {args.artifact_dir!r} is not a directory")
        return 1
    summary = agg.aggregate_dir(args.artifact_dir, out_trace=args.out,
                                out_metrics=args.metrics_out,
                                profile_dir=args.profile_dir)
    if not summary["sources"]:
        print(f"obs_aggregate: no artifacts in {args.artifact_dir!r} "
              "(expected *.trace.json / *.metrics.json / *.events.jsonl "
              "or crash-*.zip)")
        return 1
    if args.json:
        print(json.dumps(summary, indent=1))
    else:
        print(f"obs_aggregate: merged {len(summary['sources'])} "
              f"process(es) {summary['sources']} -> "
              f"{summary['lanes']} lane(s) "
              f"({summary['device_lanes']} device), "
              f"{summary['trace_events']} spans, "
              f"{summary['merged_events']} events; wrote "
              f"{summary['merged_trace']} and "
              f"{summary['merged_metrics']} (open the trace at "
              "https://ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
