"""Device-phase attribution for the wave grower's per-iteration residual.

`BENCH` records through round 5 carried a ``phase_other_ms`` grab-bag —
"gradients, score updates, top-k, tree-assembly scatters, per-round fixed
costs" — that had grown to a THIRD of the measured iteration (46.7-50.5 ms
of ~152-166 ms/iter) with no number attached to any of its parts.  The
reference itemizes every phase under USE_TIMETAG
(include/LightGBM/utils/common.h:1054-1138); this module is the TPU-side
analog: it decomposes the residual into NAMED sub-phases, each measured
with the same two-length-scan differential the headline bench uses
(utils/timer.scan_differential_ms — one jitted ``lax.scan`` per probe so
dispatch latency cancels), priced over the REPLAYED wave round schedule.

Sub-phases (ms per iteration):

* ``grad_g3_ms``        — objective gradients + (N, 3) g3 assembly, once
                          per class per iteration (models/gbdt.py step).
* ``score_update_ms``   — train-score application via the gather-free
                          ``leaf_lookup`` + the valid-set leaf-value
                          gather adds (models/gbdt.py deferred updates).
* ``topk_rank_ms``      — ``_topk_by_rank`` frontier ranking, per round.
* ``assembly_scatter_ms`` — the per-round bookkeeping commit: the store
                          write (frontier + node tables — the REAL
                          ``_PackedStore``/``_FieldStore`` code objects
                          the grower's body calls) plus the per-leaf
                          histogram-state scatter.
* ``child_meta_ms``     — per-round frontier reads, smaller-child
                          subtraction + child interleave
                          (``subtract_child_hists``), and the child
                          metadata stacks.
* ``loop_fixed_ms``     — while-loop + slot-bucket ``lax.switch``
                          control overhead per round, measured on a
                          realistic small carry.

Everything not in this list stays in ``phase_other_unattributed_ms``;
``utils/timer.PhaseBreakdown`` computes that remainder by construction
and flags the record when it exceeds 10% of the measured per-iteration
wall — the residual can never silently regrow past the bar again.

Round 12 adds the SPLIT-phase decomposition (``measure_split_breakdown``)
— the 22.8 ms/iter ``phase_split_ms`` target from r05 broken into the
fused scan's stages (ops/split.py ``scan_left_sums`` /
``scan_direction_gains`` / ``scan_pick``), timed on the same real code
objects the split search composes.

Standalone: ``JAX_PLATFORMS=cpu python tools/phase_attrib.py`` prints a
small-shape JSON breakdown (the CPU test drives the same entry point).
"""

from __future__ import annotations

import json
import os
import sys
from types import SimpleNamespace

import numpy as np

# standalone invocation from anywhere: make the repo root importable
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    try:
        import lightgbmv1_tpu  # noqa: F401
    except ImportError:
        sys.path.insert(0, _ROOT)


def _fake_split_result(rng, n, W, scalar=False):
    """SplitResult-shaped namespace for driving the store codecs at bench
    shapes (only the fields the stores read)."""
    import jax.numpy as jnp

    def arr(v, dtype):
        a = jnp.asarray(v, dtype)
        return a[0] if scalar else a

    return SimpleNamespace(
        gain=arr(np.abs(rng.randn(n)).astype(np.float32), jnp.float32),
        feature=arr(rng.randint(0, 28, n), jnp.int32),
        threshold_bin=arr(rng.randint(0, 63, n), jnp.int32),
        default_left=arr(rng.rand(n) < 0.5, bool),
        left_sum=jnp.asarray(rng.randn(n, 3).astype(np.float32))[0 if scalar
                                                                 else slice(None)],
        right_sum=jnp.asarray(rng.randn(n, 3).astype(np.float32))[0 if scalar
                                                                  else slice(None)],
        is_cat=arr(np.zeros(n, bool), bool),
        cat_bitset=(jnp.zeros(W, jnp.uint32) if scalar
                    else jnp.zeros((n, W), jnp.uint32)),
    )


def measure_grad_g3_ms(N, objective=None, label=None, reps=(4, 16),
                       probes=5):
    """Gradient + g3 assembly at N rows (one class).  With ``objective``
    (an initialized objectives.ObjectiveFunction) the REAL gradient op is
    timed; otherwise the binary-logistic formula at the same shapes."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from lightgbmv1_tpu.utils.timer import scan_differential_ms

    rng = np.random.RandomState(5)
    score = jnp.asarray(rng.randn(N).astype(np.float32))
    if label is None:
        label = jnp.asarray((rng.rand(N) < 0.5).astype(np.float32))

    def grads(s):
        if objective is not None:
            return objective.get_gradients(s)
        p = jax.nn.sigmoid(s)
        return p - label, p * (1.0 - p)

    def make(r):
        @jax.jit
        def reps_fn():
            def body(c, i):
                s = score * (1.0 + 1e-6 * i.astype(jnp.float32))
                g, h = grads(s)
                g3 = jnp.stack([g, h, jnp.ones_like(g)], axis=1)
                return c + g3.sum(), None
            s, _ = lax.scan(body, jnp.float32(0), jnp.arange(r))
            return s
        return reps_fn

    return scan_differential_ms(make, *reps, probes=probes)


def measure_score_update_ms(N, L, n_valid=0, reps=(4, 16), probes=5):
    """Train-score application (gather-free leaf_lookup + add) plus the
    valid-set leaf-value gather add — the deferred score bookkeeping of
    models/gbdt.py's fused step, one class."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from lightgbmv1_tpu.models.tree import leaf_lookup
    from lightgbmv1_tpu.utils.timer import scan_differential_ms

    rng = np.random.RandomState(6)
    table = jnp.asarray(rng.randn(L).astype(np.float32))
    lids = jnp.asarray(rng.randint(0, L, N).astype(np.int32))
    score = jnp.asarray(rng.randn(N).astype(np.float32))
    vlids = (jnp.asarray(rng.randint(0, L, n_valid).astype(np.int32))
             if n_valid else None)
    vscore = (jnp.asarray(rng.randn(n_valid).astype(np.float32))
              if n_valid else None)

    def make(r):
        @jax.jit
        def reps_fn():
            def body(c, i):
                t = table * (1.0 + 1e-6 * i.astype(jnp.float32))
                out = score + leaf_lookup(t, lids)
                acc = out.sum()
                if vlids is not None:
                    acc = acc + (vscore + t[vlids]).sum()
                return c + acc, None
            s, _ = lax.scan(body, jnp.float32(0), jnp.arange(r))
            return s
        return reps_fn

    return scan_differential_ms(make, *reps, probes=probes)


def measure_topk_rank_ms(L, K, reps=(8, 64), probes=5):
    """One ``_topk_by_rank`` frontier ranking (per wave round).  Small op
    — high rep counts keep the differential above tunnel noise."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from lightgbmv1_tpu.models.grower_wave import _topk_by_rank
    from lightgbmv1_tpu.utils.timer import scan_differential_ms

    rng = np.random.RandomState(7)
    gains = jnp.asarray(rng.randn(L).astype(np.float32))

    def make(r):
        @jax.jit
        def reps_fn():
            def body(c, i):
                vals, leafs = _topk_by_rank(
                    gains * (1.0 + 1e-6 * i.astype(jnp.float32)), K)
                return c + vals.sum() + leafs.sum().astype(jnp.float32), None
            s, _ = lax.scan(body, jnp.float32(0), jnp.arange(r))
            return s
        return reps_fn

    return scan_differential_ms(make, *reps, probes=probes)


def _round_write_inputs(rng, L, L1, K, W, F, B):
    """Synthetic per-round write record at bench shapes (indices fixed
    across reps; values perturbed by the caller to defeat CSE)."""
    import jax.numpy as jnp

    leafs = jnp.asarray(rng.choice(L // 2, K, replace=False).astype(np.int32))
    nls = jnp.asarray((L // 2 + np.arange(K)).astype(np.int32))
    nodes = jnp.asarray((L // 2 - 1 + np.arange(K)).astype(np.int32))
    cidx = jnp.stack([leafs, nls], axis=1).reshape(2 * K)
    res = _fake_split_result(rng, 2 * K, W)
    k3 = rng.randn(K, 3).astype(np.float32)
    return dict(
        res=res,
        cgain=res.gain,
        cidx=cidx, nidx=nodes,
        lidx=leafs, nlidx=nls,
        fix_l=jnp.asarray(rng.randint(0, L1, K).astype(np.int32)),
        fix_r=jnp.asarray(rng.randint(0, L1, K).astype(np.int32)),
        leafs=leafs, nls=nls,
        feats=jnp.asarray(rng.randint(0, F, K).astype(np.int32)),
        thrs=jnp.asarray(rng.randint(0, B, K).astype(np.int32)),
        dls=jnp.asarray(rng.rand(K) < 0.5),
        iscats=jnp.zeros(K, bool),
        bitsets=jnp.zeros((K, W), jnp.uint32),
        mtypes=jnp.zeros(K, jnp.int32),
        vals=jnp.asarray(np.abs(rng.randn(K)).astype(np.float32)),
        pout=jnp.asarray(rng.randn(K).astype(np.float32)),
        psum=jnp.asarray(np.abs(k3)),
        lsums=jnp.asarray(np.abs(k3) * 0.5),
        rsums=jnp.asarray(np.abs(k3) * 0.5),
        csums=jnp.asarray(np.abs(rng.randn(2 * K, 3).astype(np.float32))),
        out_l=jnp.asarray(rng.randn(K).astype(np.float32)),
        out_r=jnp.asarray(rng.randn(K).astype(np.float32)),
        couts=jnp.asarray(rng.randn(2 * K).astype(np.float32)),
        cdepth=jnp.asarray(rng.randint(1, 12, 2 * K).astype(np.int32)),
        cconstr=jnp.zeros((2 * K, 2), jnp.float32),
        num_leaves_new=jnp.asarray(L, jnp.int32),
    )


def measure_assembly_scatter_ms(L, K, F, B, fused=True, use_sub=True,
                                reps=(4, 16), probes=5):
    """One per-round bookkeeping commit: the REAL store write path
    (grower_wave._PackedStore / _FieldStore — the same code objects the
    grower's while-loop body calls) plus the per-leaf histogram-state
    scatter.  This is the sub-phase the fused_bookkeeping lever targets:
    the packed store commits in 3 coalesced scatters, the legacy store in
    ~30 per-field ones."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from lightgbmv1_tpu.models.grower_wave import _FieldStore, _PackedStore
    from lightgbmv1_tpu.utils.timer import scan_differential_ms

    L1 = max(L - 1, 1)
    W = -(-B // 32)
    store = (_PackedStore if fused else _FieldStore)(L, L1, W, False, False)
    rng = np.random.RandomState(8)
    s0 = store.init(_fake_split_result(rng, 1, W, scalar=True),
                    jnp.float32(0.1))
    r0 = _round_write_inputs(rng, L, L1, K, W, F, B)
    hist = jnp.asarray(rng.randn(2 * K, F, B, 3).astype(np.float32))
    leaf_hist0 = jnp.zeros((L, F, B, 3), jnp.float32) if use_sub else None

    def make(r):
        @jax.jit
        def reps_fn():
            def body(carry, i):
                s, lh = carry
                pert = 1.0 + 1e-6 * i.astype(jnp.float32)
                rr = dict(r0)
                rr["vals"] = r0["vals"] * pert
                rr["cgain"] = r0["cgain"] * pert
                s = store.write(s, rr)
                if lh is not None:
                    if store.fused:
                        lh = lh.at[r0["cidx"]].set(hist * pert, mode="drop")
                    else:
                        lh = lh.at[r0["lidx"]].set(hist[0::2] * pert,
                                                   mode="drop")
                        lh = lh.at[r0["nlidx"]].set(hist[1::2] * pert,
                                                    mode="drop")
                return (s, lh), None
            (s, lh), _ = lax.scan(body, (s0, leaf_hist0), jnp.arange(r))
            out = store.gains(s).sum()
            if lh is not None:
                out = out + lh.sum()
            return out
        return reps_fn

    return scan_differential_ms(make, *reps, probes=probes)


def measure_child_meta_ms(L, K, F, B, fused=True, reps=(4, 16), probes=5):
    """Per-round frontier reads + smaller-child subtraction/interleave +
    child metadata stacks (grower_wave body between the histogram pass
    and split finding)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from lightgbmv1_tpu.models.grower_wave import (_FieldStore, _PackedStore,
                                                   subtract_child_hists)
    from lightgbmv1_tpu.utils.timer import scan_differential_ms

    L1 = max(L - 1, 1)
    W = -(-B // 32)
    store = (_PackedStore if fused else _FieldStore)(L, L1, W, False, False)
    rng = np.random.RandomState(9)
    s0 = store.init(_fake_split_result(rng, 1, W, scalar=True),
                    jnp.float32(0.1))
    leafs = jnp.asarray(rng.choice(L // 2, K, replace=False).astype(np.int32))
    order_c = jnp.arange(K, dtype=jnp.int32)
    h_slot = jnp.asarray(rng.randn(K, F, B, 3).astype(np.float32))
    leaf_hist = jnp.asarray(rng.randn(L, F, B, 3).astype(np.float32))
    nls = jnp.asarray((L // 2 + np.arange(K)).astype(np.int32))

    def make(r):
        @jax.jit
        def reps_fn():
            def body(c, i):
                pert = 1.0 + 1e-6 * i.astype(jnp.float32)
                rd = store.read(s0, leafs)
                sm_left = rd["lsums"][:, 2] <= rd["rsums"][:, 2]
                hist, _, _ = subtract_child_hists(
                    h_slot * pert, leaf_hist, leafs, order_c, sm_left)
                csums = jnp.stack([rd["lsums"], rd["rsums"]],
                                  axis=1).reshape(2 * K, 3)
                d = rd["pdepth"] + 1
                cdepth = jnp.stack([d, d], axis=1).reshape(2 * K)
                cleafs = jnp.stack([leafs, nls], axis=1).reshape(2 * K)
                return (c + hist.sum() + csums.sum()
                        + cdepth.sum().astype(jnp.float32)
                        + cleafs.sum().astype(jnp.float32)
                        + rd["pout"].sum()), None
            s, _ = lax.scan(body, jnp.float32(0), jnp.arange(r))
            return s
        return reps_fn

    return scan_differential_ms(make, *reps, probes=probes)


def measure_loop_fixed_ms(L, n_buckets=3, n_rounds=10, reps=(4, 16),
                          probes=5):
    """While-loop + slot-bucket lax.switch control overhead, per round:
    one while_loop of ``n_rounds`` iterations whose body evaluates the
    cond-style frontier max and a ``lax.switch`` over ``n_buckets``
    branches on a small carry — the schedule scaffolding the real round
    body runs around its compute."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from lightgbmv1_tpu.utils.timer import scan_differential_ms

    rng = np.random.RandomState(10)
    gains0 = jnp.asarray(np.abs(rng.randn(L)).astype(np.float32) + 1.0)

    def one_loop(gains):
        def cond(carry):
            i, g = carry
            return (i < n_rounds) & (jnp.max(g) > 0)

        def body(carry):
            i, g = carry
            s_idx = jnp.clip(i % n_buckets, 0, n_buckets - 1)
            g = lax.switch(s_idx, [
                (lambda gg, f=float(b + 1): gg * (1.0 + 1e-7 * f))
                for b in range(n_buckets)
            ], g)
            return i + 1, g

        _, g = lax.while_loop(cond, body, (jnp.int32(0), gains))
        return g

    def make(r):
        @jax.jit
        def reps_fn():
            def body(c, i):
                g = one_loop(gains0 * (1.0 + 1e-6 * i.astype(jnp.float32)))
                return c + g.sum(), None
            s, _ = lax.scan(body, jnp.float32(0), jnp.arange(r))
            return s
        return reps_fn

    return scan_differential_ms(make, *reps, probes=probes) / n_rounds


def measure_split_breakdown(*, F, B, K, rounds_per_iter, meta=None,
                            params=None, num_class=1, reps=(8, 64),
                            probes=5):
    """Named decomposition of ``phase_split_ms`` into the fused scan's
    three stages (ops/split.py — the REAL module-level code objects the
    split search composes, so the attribution cannot drift from what
    training runs), each vmapped over the 2K children of a wave round and
    priced over the round schedule:

    * ``split_cumsum_ms`` — ``scan_left_sums``: the cumulative-sum pass +
      missing-mass adjustments building the (2, F, B, 3) stacked left
      sums (the int8sr dequantize multiply folds here).
    * ``split_gain_ms``   — ``scan_direction_gains``: the stacked
      both-direction gain evaluation + penalty chain.
    * ``split_pick_ms``   — ``scan_pick``: the tie-band preference argmax
      and winner decode.

    Returns a utils.timer.PhaseBreakdown; bench.py records it against
    the measured ``phase_split_ms`` so the remainder (vmap plumbing,
    result assembly, categorical search when present) is explicit."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from lightgbmv1_tpu.ops.split import (NO_CONSTRAINT, scan_direction_gains,
                                          scan_left_sums, scan_pick)
    from lightgbmv1_tpu.utils.timer import PhaseBreakdown, scan_differential_ms

    if meta is None or params is None:
        from lightgbmv1_tpu.ops.split import FeatureMeta, SplitParams

        if params is None:
            params = SplitParams()
        if meta is None:
            meta = FeatureMeta(
                num_bins=jnp.full(F, B, jnp.int32),
                missing_type=jnp.zeros(F, jnp.int32),
                nan_bin=jnp.full(F, -1, jnp.int32),
                zero_bin=jnp.zeros(F, jnp.int32),
                is_categorical=jnp.zeros(F, bool),
                usable=jnp.ones(F, bool),
                monotone_type=jnp.zeros(F, jnp.int32),
            )
    rng = np.random.RandomState(12)
    C = 2 * K                                  # children per round
    h2k = jnp.asarray(np.abs(rng.randn(C, F, B, 3)).astype(np.float32))
    parents = h2k.sum(axis=(1, 2))             # (C, 3)
    mask = jnp.ones(F, bool)
    nc = jnp.asarray(NO_CONSTRAINT, jnp.float32)
    left2 = jax.vmap(lambda h: scan_left_sums(h, meta)[0])(h2k)
    gains0, shift0 = jax.vmap(
        lambda l2, p: scan_direction_gains(l2, p, meta, mask, params, nc)
    )(left2, parents)

    def make_stage(fn):
        def make(r):
            @jax.jit
            def reps_fn():
                def body(c, i):
                    return c + fn(1.0 + 1e-6 * i.astype(jnp.float32)), None
                s, _ = lax.scan(body, jnp.float32(0), jnp.arange(r))
                return s
            return reps_fn
        return make

    def cumsum_stage(pert):
        l2, _ = jax.vmap(lambda h: scan_left_sums(h * pert, meta))(h2k)
        return l2.sum()

    def gain_stage(pert):
        g, _ = jax.vmap(
            lambda l2, p: scan_direction_gains(l2 * pert, p, meta, mask,
                                               params, nc)
        )(left2, parents)
        return jnp.where(jnp.isfinite(g), g, 0.0).sum()

    def pick_stage(pert):
        bg, ft, th, dr = jax.vmap(
            lambda g, s: scan_pick(g * pert, s, meta))(gains0, shift0)
        return (jnp.where(jnp.isfinite(bg), bg, 0.0).sum()
                + (ft + th + dr).sum().astype(jnp.float32))

    R = float(rounds_per_iter) * num_class
    bd = PhaseBreakdown()
    bd.add("split_cumsum_ms",
           scan_differential_ms(make_stage(cumsum_stage), *reps,
                                probes=probes) * R)
    bd.add("split_gain_ms",
           scan_differential_ms(make_stage(gain_stage), *reps,
                                probes=probes) * R)
    bd.add("split_pick_ms",
           scan_differential_ms(make_stage(pick_stage), *reps,
                                probes=probes) * R)
    return bd


def measure_other_breakdown(*, N, F, B, L, K, rounds_per_iter,
                            n_buckets=3, n_valid=0, num_class=1,
                            objective=None, fused=True, use_sub=True,
                            reps=(4, 16), probes=5):
    """Full named decomposition of the per-iteration ``phase_other_ms``
    residual at the given shapes.  Returns a utils.timer.PhaseBreakdown;
    callers (bench.py) pass it the measured residual + wall to emit the
    ``phase_other_breakdown`` record fields."""
    from lightgbmv1_tpu.utils.timer import PhaseBreakdown

    R = float(rounds_per_iter)
    bd = PhaseBreakdown()
    bd.add("grad_g3_ms",
           measure_grad_g3_ms(N, objective=objective, reps=reps,
                              probes=probes) * num_class)
    bd.add("score_update_ms",
           measure_score_update_ms(N, L, n_valid=n_valid, reps=reps,
                                   probes=probes) * num_class)
    topk_reps = (reps[0] * 2, reps[1] * 4)   # small ops: longer scans
    bd.add("topk_rank_ms",
           measure_topk_rank_ms(L, K, reps=topk_reps, probes=probes)
           * R * num_class)
    bd.add("assembly_scatter_ms",
           measure_assembly_scatter_ms(L, K, F, B, fused=fused,
                                       use_sub=use_sub, reps=reps,
                                       probes=probes) * R * num_class)
    bd.add("child_meta_ms",
           measure_child_meta_ms(L, K, F, B, fused=fused, reps=reps,
                                 probes=probes) * R * num_class)
    bd.add("loop_fixed_ms",
           measure_loop_fixed_ms(L, n_buckets=n_buckets, reps=topk_reps,
                                 probes=probes) * R * num_class)
    return bd


# Canonical per-iteration phase fields (BENCH record keys).  The single
# source of truth for "what counts as a phase" — bench.py's phase
# profile and the roofline join both build their {phase: ms} dicts from
# this list, so a NEW phase (the fused wave-round kernel's single merged
# hist+split row, ISSUE 13) lands as its own labeled row everywhere
# instead of silently pooling into phase_other.  Order is render order.
PHASE_MS_KEYS = (
    "phase_hist_ms",
    "phase_partition_ms",
    "phase_valid_route_ms",
    "phase_split_ms",
    # hist_method=fused (ISSUE 15, the single-pass wave round):
    # partition + valid routing + histogram + smaller-child subtraction
    # + split scan + top-k are ONE labeled dispatch — one merged phase,
    # mutually exclusive with the staged hist/partition/valid_route/
    # split rows for the run that produced it
    "phase_round_fused_ms",
    # wave_loop_rounds>1 (ISSUE 17, the persistent multi-round wave
    # loop): R consecutive rounds — frontier state resident in VMEM —
    # are ONE labeled dispatch; mutually exclusive with BOTH the staged
    # rows and the single-round fused row for the run that produced it
    "phase_wave_loop_ms",
    "phase_other_ms",
)

# pre-ISSUE-15 records carried the merged fused row WITHOUT partition
# folded in under this name; renders as the same row so old captures
# keep their phase profile
_LEGACY_PHASE_ALIASES = {
    "phase_hist_split_fused_ms": "phase_round_fused_ms",
}


def phase_ms_from_fields(fields):
    """``{phase: ms}`` from a BENCH record's phase fields, stripping the
    ``phase_``/``_ms`` wrapping — every positive canonical phase,
    including the fused merged row.  Consumers (bench.py's trace phase
    profile and the roofline join) go through here so the phase list
    cannot drift per call site.  Legacy field names
    (``_LEGACY_PHASE_ALIASES``) land on their canonical row."""
    out = {}
    fields = dict(fields or {})
    for legacy, canon in _LEGACY_PHASE_ALIASES.items():
        if fields.get(canon) is None and fields.get(legacy) is not None:
            fields[canon] = fields[legacy]
    for k in PHASE_MS_KEYS:
        v = fields.get(k)
        if isinstance(v, (int, float)) and v > 0:
            out[k[len("phase_"):-len("_ms")]] = v
    return out


def split_cost_by_ms(total_flops, total_bytes, phase_ms):
    """Attribute ONE compiled executable's cost analysis (flops, bytes
    accessed — obs/xla.py compile telemetry of the fused/scanned train
    step) over the measured per-phase milliseconds, proportionally.

    This is an ESTIMATE by construction (XLA reports whole-executable
    totals; the proportionality assumption is that arithmetic intensity
    is uniform across phases) — the honest per-phase ground truth is the
    profiler lane, but the proportional table is what makes the roofline
    column computable from an always-on capture.  Returns the
    ``{phase: {"flops", "bytes"}}`` cost table
    :func:`roofline_attribution` consumes, or ``{}`` when either input
    is missing."""
    total_ms = sum(v for v in (phase_ms or {}).values()
                   if isinstance(v, (int, float)) and v > 0)
    if not total_ms or not (total_flops or total_bytes):
        return {}
    table = {}
    for phase, ms in phase_ms.items():
        if not isinstance(ms, (int, float)) or ms <= 0:
            continue
        frac = ms / total_ms
        table[phase] = {
            "flops": float(total_flops) * frac if total_flops else None,
            "bytes": float(total_bytes) * frac if total_bytes else None,
        }
    return table


def roofline_attribution(phase_ms, cost_table, peak_flops_per_s,
                         peak_bytes_per_s=None):
    """Per-phase achieved-fraction-of-peak: join cost-analysis flops /
    bytes (``cost_table`` — ``{phase: {"flops", "bytes"}}``, e.g. from
    :func:`split_cost_by_ms` or a per-phase profiler capture) with the
    MEASURED phase milliseconds against the device ceilings.

    Per phase: ``achieved_tf_s = flops / s / 1e12`` and
    ``frac_of_peak_flops`` against ``peak_flops_per_s``;
    ``achieved_gb_s`` / ``frac_of_peak_bw`` against ``peak_bytes_per_s``
    when given.  ``frac_of_peak`` is the max of the two (the roofline:
    a kernel is as good as its binding resource) and ``bound`` names
    which resource binds.  Phases missing ms or cost rows are omitted —
    absent truth is absent, never zero-filled."""
    rows = {}
    for phase, ms in (phase_ms or {}).items():
        if not isinstance(ms, (int, float)) or ms <= 0:
            continue
        cost = (cost_table or {}).get(phase) or {}
        flops = cost.get("flops")
        nbytes = cost.get("bytes")
        if not flops and not nbytes:
            continue
        sec = ms / 1e3
        row = {"ms": round(float(ms), 3)}
        frac_f = frac_b = None
        if flops and peak_flops_per_s:
            row["achieved_tf_s"] = round(flops / sec / 1e12, 4)
            frac_f = flops / sec / float(peak_flops_per_s)
            row["frac_of_peak_flops"] = round(frac_f, 4)
        if nbytes and peak_bytes_per_s:
            row["achieved_gb_s"] = round(nbytes / sec / 1e9, 3)
            frac_b = nbytes / sec / float(peak_bytes_per_s)
            row["frac_of_peak_bw"] = round(frac_b, 4)
        candidates = [f for f in (frac_f, frac_b) if f is not None]
        if not candidates:
            continue
        row["frac_of_peak"] = round(max(candidates), 4)
        row["bound"] = ("compute"
                        if frac_f is not None
                        and (frac_b is None or frac_f >= frac_b)
                        else "memory")
        rows[phase] = row
    return rows


def main():
    """Standalone small-shape run (CPU-safe); prints one JSON line."""
    bd = measure_other_breakdown(N=20_000, F=8, B=16, L=31, K=8,
                                 rounds_per_iter=6.0, n_valid=2_000,
                                 probes=3)
    sbd = measure_split_breakdown(F=8, B=16, K=8, rounds_per_iter=6.0,
                                  probes=3)
    print(json.dumps({"phase_other_breakdown": bd.parts,
                      "attributed_ms": round(bd.total_attributed(), 3),
                      "phase_split_breakdown": sbd.parts,
                      "split_attributed_ms": round(
                          sbd.total_attributed(), 3)}))


if __name__ == "__main__":
    main()
