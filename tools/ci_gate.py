"""One CI gate: the bench regression sentinel + the tier-1 wall budget.

Two guards existed as separate tools with separate exit codes
(tools/bench_trend.py, tools/tier1_budget.py); driver/CI wiring wants
ONE entry with ONE exit code, so a capture or a suite run is gated by a
single command:

    python tools/ci_gate.py [--records DIR] [--t1-log PATH]
                            [--skip-trend] [--skip-t1]

* **trend** — ``bench_trend.run()`` over the record directory: the
  newest BENCH/MULTICHIP record must not regress a watched field >10%
  vs the best prior capture nor read False on any ``*_ok`` guard.
* **tier1** — ``tier1_budget`` over the per-test durations JSONL (or the
  tee'd pytest log): the projected tier-1 wall must fit 95% of the
  870 s driver budget.  A MISSING log fails the gate (a guard that
  silently skips is not a guard) unless ``--skip-t1`` says the caller
  genuinely has no suite run to judge (e.g. a records-only capture box).

Exit code 0 only when every enabled guard passes; each guard's own
report is printed so the failing one is obvious.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import bench_trend  # noqa: E402
import tier1_budget  # noqa: E402


def run_gate(records_dir: str, t1_log: str, skip_trend: bool = False,
             skip_t1: bool = False, budget: float = None,
             frac: float = None, out=print) -> dict:
    """Run both guards; returns ``{"trend_ok", "t1_ok", "ok"}`` (skipped
    guards report True and are marked in the dict)."""
    results = {"trend_ok": True, "t1_ok": True,
               "trend_skipped": bool(skip_trend),
               "t1_skipped": bool(skip_t1)}
    if not skip_trend:
        trend = bench_trend.run(records_dir)
        bench_trend.render_report(trend, out=out)
        results["trend_ok"] = bool(trend["ok"])
    else:
        out("ci_gate: trend guard SKIPPED")
    if not skip_t1:
        if not os.path.exists(t1_log):
            out(f"ci_gate: tier-1 log {t1_log!r} not found — the budget "
                "guard cannot run, FAILING the gate (pass --skip-t1 for "
                "a records-only check)")
            results["t1_ok"] = False
        else:
            per_test, wall = tier1_budget.load(t1_log)
            kw = {}
            if budget is not None:
                kw["budget"] = budget
            if frac is not None:
                kw["frac"] = frac
            results["t1_ok"] = bool(
                tier1_budget.report(per_test, wall, out=out, **kw))
    else:
        out("ci_gate: tier-1 budget guard SKIPPED")
    results["ok"] = results["trend_ok"] and results["t1_ok"]
    out(f"ci_gate: {'PASS' if results['ok'] else 'FAIL'} "
        f"(trend_ok={results['trend_ok']}, t1_ok={results['t1_ok']})")
    return results


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--records", default=bench_trend.ROOT,
                    help="BENCH_r*/MULTICHIP_r* record directory")
    ap.add_argument("--t1-log", default="/tmp/_t1.log",
                    help="tier-1 durations JSONL or tee'd pytest log")
    ap.add_argument("--skip-trend", action="store_true")
    ap.add_argument("--skip-t1", action="store_true")
    ap.add_argument("--budget", type=float, default=None)
    ap.add_argument("--frac", type=float, default=None)
    args = ap.parse_args(argv)
    results = run_gate(args.records, args.t1_log,
                       skip_trend=args.skip_trend, skip_t1=args.skip_t1,
                       budget=args.budget, frac=args.frac)
    return 0 if results["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
