"""One CI gate: the bench regression sentinel + the tier-1 wall budget.

Two guards existed as separate tools with separate exit codes
(tools/bench_trend.py, tools/tier1_budget.py); driver/CI wiring wants
ONE entry with ONE exit code, so a capture or a suite run is gated by a
single command:

    python tools/ci_gate.py [--records DIR] [--t1-log PATH]
                            [--skip-trend] [--skip-t1]

* **trend** — ``bench_trend.run()`` over the record directory: the
  newest BENCH/MULTICHIP record must not regress a watched field >10%
  vs the best prior capture nor read False on any ``*_ok`` guard.
* **tier1** — ``tier1_budget`` over the per-test durations JSONL (or the
  tee'd pytest log): the projected tier-1 wall must fit 95% of the
  870 s driver budget.  A MISSING log fails the gate (a guard that
  silently skips is not a guard) unless ``--skip-t1`` says the caller
  genuinely has no suite run to judge (e.g. a records-only capture box).
* **required guards** — ``--require-guards obs_ok,slo_ok,forensics_ok``
  (ISSUE 10): the NEWEST BENCH record must CONTAIN each named guard and
  hold it True.  The trend sentinel only flags a guard that is present
  and False; this check additionally fails a capture that silently
  dropped the field (a guard that vanishes is a guard that failed).
  Off by default so records predating a guard still gate cleanly;
  driver captures after ISSUE 11 pass ``--require-guards`` with the
  full set in :data:`REQUIRED_GUARDS` (obs/slo/forensics/chaos plus the
  fleet guards ``fleet_ok`` and ``chaos_fleet_ok``) — or simply
  ``--require-guards default``, which expands to it.

Exit code 0 only when every enabled guard passes; each guard's own
report is printed so the failing one is obvious.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import bench_trend  # noqa: E402
import tier1_budget  # noqa: E402

# the full post-ISSUE-14 driver guard set: ``--require-guards default``
# expands to this, so the driver command line stops rotting as guards
# are added (a new *_ok lands here in the same PR that records it);
# obs_device_ok is the device-truth telemetry guard (compile counters,
# serving zero-retrace, HBM/ledger reconciliation — bench.py
# measure_obs); fused_ok is the fused wave-round megakernel guard
# (bit parity with the staged path AND, on device, the merged
# hist+split round at or under the staged phases — bench.py
# measure_fused / measure_fused_round_ms); drift_ok is the
# model-quality guard (skew-injection probe detected + zero clean
# false alarms + streamed-vs-resident reference byte parity + armed
# sampling within the <= 2% serving contract — bench.py measure_drift);
# fused_round_ok is the single-pass wave-round guard (ISSUE 15: routed
# parity with partition + valid routing + top-k folded into the fused
# dispatch AND the binned-matrix-read-once bytes contract — >= 1.8x
# bytes_accessed reduction vs staged partition+hist on device);
# hier_comm_ok is the pod-scale two-level collective guard (ISSUE 16:
# DCN histogram bytes <= flat reduce-scatter wire / num_hosts, and the
# voting learner's DCN payload <= its top-2k analytic bound —
# parallel/cluster.py hier_comm_table_per_round); fused_loop_ok is the
# persistent multi-round wave-loop guard (ISSUE 17: wave_loop_rounds>1
# model-text parity with the single-round fused path everywhere AND, on
# device, the looped per-iteration wall at or under the single-round
# wall it replaces — bench.py measure_fused_waveloop);
# predict_fused_ok is the serving-megakernel guard (ISSUE 19: fused
# walk+accumulate node/bit parity with the host oracle, zero retraces
# within a bucket, and on device >= 1.5x the scan walk's compute rate
# with cost_analysis bytes confirming the single-read contract —
# bench.py measure_predict); tenant_ok is the multi-tenant serving
# guard (ISSUE 20: cross-tenant compile-bucket sharing proven by
# per-label counters — the second tenant's warm adds zero compiles,
# zero retraces under mixed traffic — plus fair-share isolation under
# a 2x hot-tenant overload, per-tenant publish/rollback parity and the
# SLO-driven placement-move drill — bench.py measure_tenants)
REQUIRED_GUARDS = ("obs_ok", "slo_ok", "forensics_ok", "chaos_ok",
                   "fleet_ok", "chaos_fleet_ok", "obs_device_ok",
                   "fused_ok", "drift_ok", "fused_round_ok",
                   "hier_comm_ok", "fused_loop_ok", "packed_ok",
                   "predict_fused_ok", "tenant_ok")


def check_required_guards(records_dir: str, guards, out=print) -> bool:
    """The newest BENCH record must carry every named guard as True —
    present-and-True, not merely not-False (a capture that dropped the
    field fails)."""
    records = bench_trend.load_bench_records(records_dir)
    if not records:
        out("ci_gate: --require-guards with NO bench records — FAIL")
        return False
    name, newest = records[-1]
    ok = True
    for g in guards:
        v = newest.get(g)
        if v is True:
            out(f"ci_gate: required guard {g} = True ({name})")
        else:
            out(f"ci_gate: required guard {g} "
                f"{'MISSING from' if g not in newest else f'= {v} in'} "
                f"{name} — FAIL")
            ok = False
    return ok


def run_gate(records_dir: str, t1_log: str, skip_trend: bool = False,
             skip_t1: bool = False, budget: float = None,
             frac: float = None, require_guards=(), out=print) -> dict:
    """Run the guards; returns ``{"trend_ok", "t1_ok", "guards_ok",
    "ok"}`` (skipped guards report True and are marked in the dict)."""
    results = {"trend_ok": True, "t1_ok": True, "guards_ok": True,
               "trend_skipped": bool(skip_trend),
               "t1_skipped": bool(skip_t1)}
    if not skip_trend:
        trend = bench_trend.run(records_dir)
        bench_trend.render_report(trend, out=out)
        results["trend_ok"] = bool(trend["ok"])
    else:
        out("ci_gate: trend guard SKIPPED")
    if require_guards:
        results["guards_ok"] = check_required_guards(
            records_dir, require_guards, out=out)
    if not skip_t1:
        if not os.path.exists(t1_log):
            out(f"ci_gate: tier-1 log {t1_log!r} not found — the budget "
                "guard cannot run, FAILING the gate (pass --skip-t1 for "
                "a records-only check)")
            results["t1_ok"] = False
        else:
            per_test, wall = tier1_budget.load(t1_log)
            kw = {}
            if budget is not None:
                kw["budget"] = budget
            if frac is not None:
                kw["frac"] = frac
            results["t1_ok"] = bool(
                tier1_budget.report(per_test, wall, out=out, **kw))
    else:
        out("ci_gate: tier-1 budget guard SKIPPED")
    results["ok"] = (results["trend_ok"] and results["t1_ok"]
                     and results["guards_ok"])
    out(f"ci_gate: {'PASS' if results['ok'] else 'FAIL'} "
        f"(trend_ok={results['trend_ok']}, t1_ok={results['t1_ok']}, "
        f"guards_ok={results['guards_ok']})")
    return results


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--records", default=bench_trend.ROOT,
                    help="BENCH_r*/MULTICHIP_r* record directory")
    ap.add_argument("--t1-log", default="/tmp/_t1.log",
                    help="tier-1 durations JSONL or tee'd pytest log")
    ap.add_argument("--skip-trend", action="store_true")
    ap.add_argument("--skip-t1", action="store_true")
    ap.add_argument("--budget", type=float, default=None)
    ap.add_argument("--frac", type=float, default=None)
    ap.add_argument("--require-guards", default="",
                    help="comma-separated guard fields the NEWEST bench "
                         "record must carry as True; 'default' expands "
                         "to " + ",".join(REQUIRED_GUARDS))
    args = ap.parse_args(argv)
    guards = tuple(g for g in args.require_guards.split(",") if g)
    if "default" in guards:
        guards = tuple(g for g in guards if g != "default") \
            + REQUIRED_GUARDS
    results = run_gate(args.records, args.t1_log,
                       skip_trend=args.skip_trend, skip_t1=args.skip_t1,
                       budget=args.budget, frac=args.frac,
                       require_guards=guards)
    return 0 if results["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
