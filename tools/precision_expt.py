"""500-iter AUC + wall under histogram precision variants (VERDICT r4 #6).

Done-bar: a variant within 0.0005 AUC of bf16x2 at 500 iters and >= 1.2x
its throughput.  Variants ride the depth-adaptive knob (hist_dtype_deep):
sustained (slot-bucket >= 32) rounds run the cheap dtype, ramp rounds and
the root pass keep bf16x2.  ``deep_int8sr`` additionally quantizes the
16-slot ramp bucket (the gate extension, models/grower_wave.py).

This experiment is the GATE for defaulting int8sr on: the mode ships
opt-in until a device capture of this script shows ``auc_parity`` true
(|AUC - bf16x2 AUC| <= 0.0005 at 500 iters) — the bar the round-5
rejection of plain int8 (-0.007 AUC) established.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import make_data  # noqa: E402

import jax  # noqa: E402

from lightgbmv1_tpu.config import Config  # noqa: E402
from lightgbmv1_tpu.io.dataset import BinnedDataset  # noqa: E402
from lightgbmv1_tpu.models.gbdt import create_boosting  # noqa: E402

N = int(os.environ.get("BENCH_ROWS", 1_000_000))
X, y = make_data(N, 0)
Xt, yt = make_data(100_000, 1)

base = {"objective": "binary", "num_leaves": 255, "max_bin": 63,
        "learning_rate": 0.1, "min_data_in_leaf": 20, "metric": "auc",
        "verbosity": -1, "tree_growth": "leafwise"}
cfg0 = Config.from_dict(base)
ds = BinnedDataset.from_numpy(X, label=y, config=cfg0)
dt = BinnedDataset.from_numpy(Xt, label=yt, config=cfg0, reference=ds)

VARIANTS = [
    ("bf16x2", {}),
    ("deep_bf16", {"hist_dtype_deep": "bf16"}),
    ("deep_int8", {"hist_dtype_deep": "int8"}),
    ("deep_int8sr", {"hist_dtype_deep": "int8sr"}),
    ("all_int8", {"hist_dtype": "int8"}),
]

AUC_PARITY_BAR = 0.0005     # |AUC - bf16x2| at 500 iters (VERDICT r5 #4)

auc_ref = None
for name, over in VARIANTS:
    cfg = Config.from_dict({**base, **over})
    gb = create_boosting(cfg, ds)
    gb.add_valid(dt, "test")
    gb.train_iters(100)
    jax.device_get(gb._train_scores.score)
    t0 = time.time()
    for _ in range(4):
        gb.train_iters(100)
    jax.device_get(gb._train_scores.score)
    wall500 = (time.time() - t0) * 500.0 / 400.0
    auc = None
    for (_, mname, value, _) in gb.eval_valid():
        if mname == "auc":
            auc = float(value)
    rec = {"variant": name, "wall500_s": round(wall500, 2),
           "auc500": round(auc, 6) if auc is not None else None}
    if name == "bf16x2":
        auc_ref = auc
    elif auc is not None and auc_ref is not None:
        delta = auc - auc_ref
        rec["auc_delta_vs_bf16x2"] = round(delta, 6)
        rec["auc_parity"] = bool(abs(delta) <= AUC_PARITY_BAR)
    print(json.dumps(rec), flush=True)
