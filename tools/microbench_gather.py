"""Microbenchmark: TPU costs of the ops the round-5 wave redesign leans on.

Differential two-length-scan timing (cancels the ~113 ms tunnel dispatch):
per-op seconds = (wall(R2) - wall(R1)) / (R2 - R1), median of 3.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

N = 1_000_000
F = 28
B = 64
L = 255
K = 64

rng = np.random.RandomState(0)
binned_cm = jnp.asarray(rng.randint(0, B, size=(F, N), dtype=np.uint8))
binned_rm = jnp.asarray(np.asarray(binned_cm).T.copy())
g3 = jnp.asarray(rng.randn(N, 3).astype(np.float32))
lids = jnp.asarray(rng.randint(0, L, size=N).astype(np.int32))
tab = jnp.asarray(rng.randint(0, 1 << 28, size=L).astype(np.int32))
feats_k = jnp.asarray(rng.randint(0, F, size=K).astype(np.int32))
thrs_k = jnp.asarray(rng.randint(0, B, size=K).astype(np.int32))
leafs_k = jnp.asarray(rng.randint(0, L, size=K).astype(np.int32))
CAP = N // 2

out = {}


def rec(k, v):
    out[k] = v
    print(k, round(v, 3), flush=True)


def timed(make, r1=4, r2=16):
    f1 = jax.jit(make(r1))
    f2 = jax.jit(make(r2))
    jax.block_until_ready(f1())
    jax.block_until_ready(f2())
    vals = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(f1())
        t1 = time.perf_counter()
        jax.block_until_ready(f2())
        t2 = time.perf_counter()
        vals.append(((t2 - t1) - (t1 - t0)) / (r2 - r1))
    return float(np.median(vals))


def scan_make(body):
    def make(r):
        def f():
            def step(c, i):
                return body(c, i), None
            s, _ = lax.scan(step, jnp.float32(0), jnp.arange(r))
            return s
        return f
    return make


def s_of(x):
    return jnp.sum(x.astype(jnp.float32) if x.dtype != jnp.float32 else x)


rec("A_table_gather_ms", 1e3 * timed(scan_make(
    lambda c, i: c + s_of(tab[(lids + i) % L]))))

rec("C_rowmajor_bin_gather_ms", 1e3 * timed(scan_make(
    lambda c, i: c + s_of(jnp.take_along_axis(
        binned_rm, ((lids + i) % F)[:, None], axis=1)[:, 0]))))

rec("D_colmajor_bin_gather_ms", 1e3 * timed(scan_make(
    lambda c, i: c + s_of(jnp.take_along_axis(
        binned_cm, ((lids + i) % F)[None, :], axis=0)[0]))))


def compact_idx(c, i):
    live = ((lids + i) % 2) == 0
    pos = jnp.cumsum(live.astype(jnp.int32)) - 1
    idx = jnp.zeros(CAP, jnp.int32).at[
        jnp.where(live, pos, CAP)].set(jnp.arange(N, dtype=jnp.int32),
                                       mode="drop")
    return c + s_of(idx)


rec("E_compact_index_ms", 1e3 * timed(scan_make(compact_idx)))


def row_gather(c, i):
    idx = (jnp.arange(CAP, dtype=jnp.int32) * 2 + i) % N
    bc = jnp.take(binned_rm, idx, axis=0)
    gc = jnp.take(g3, idx, axis=0)
    return c + s_of(bc) + s_of(gc)


rec("F_row_gather_half_ms", 1e3 * timed(scan_make(row_gather)))


def old_decision(c, i):
    fk = (feats_k + i) % F
    bk = jax.vmap(lambda f: binned_cm[f])(fk).astype(jnp.int32)   # (K, N)
    gl = bk <= thrs_k[:, None]
    mine = lids[None, :] == leafs_k[:, None]
    upd = jnp.sum(jnp.where(mine & (~gl), 1, 0), axis=0)
    return c + s_of(upd)


rec("G_oldKN_decision_ms", 1e3 * timed(scan_make(old_decision)))

rec("I_transpose_ms", 1e3 * timed(scan_make(
    lambda c, i: c + s_of((binned_cm + i.astype(jnp.uint8)).T))))

from lightgbmv1_tpu.ops.hist_pallas import hist_leaves_pallas  # noqa: E402

for slots, rows in [(64, N), (64, N // 2), (16, N // 2), (4, N // 2),
                    (4, N)]:
    bm = binned_rm[:rows].T.copy() if rows != N else binned_cm
    g3r = g3[:rows]
    lab = (lids[:rows] % (slots + 1)).astype(jnp.int32)

    def hist_body(c, i, bm=bm, g3r=g3r, lab=lab, slots=slots):
        h = hist_leaves_pallas(bm, g3r + i, lab, slots + 1, B,
                               precision="bf16x2")
        return c + jnp.sum(h[0, 0, 0])

    rec(f"H_hist_s{slots}_n{rows}_ms", 1e3 * timed(scan_make(hist_body), 2, 8))

print(json.dumps({k: round(v, 3) for k, v in out.items()}, indent=1))
