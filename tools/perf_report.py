"""Regenerate PERF.md from the latest captured BENCH record (VERDICT r5 #2).

Every number in PERF.md greps to a field of a ``BENCH_r*.json`` record —
stale-quote drift (the discipline item flagged in BOTH round 4 and round
5: hand-quoted figures silently outliving the capture they came from) is
structurally impossible, because PERF.md is GENERATED output:

    python tools/perf_report.py            # newest BENCH_r*.json -> PERF.md
    python tools/perf_report.py BENCH_r05.json [out.md]

Mechanism narrative (what a lever IS) lives in the module docstrings and
git history it links; THIS file holds only the record-to-table mapping
plus cross-record notes computed from the records themselves (e.g. the
r04->r05 roofline-denominator drift).
"""
import glob
import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The dryrun smoke shape (tools/dryrun_multichip, __graft_entry__.py):
# the analytic comm table below is computed at exactly these constants so
# every figure greps to a formula input, not a hand-typed number.
SMOKE = dict(ndev=8, F=16, B=64, K=16, top_k=20)


def load(path):
    with open(path) as fh:
        rec = json.load(fh)
    return rec.get("parsed", rec)


def load_multichip(root=ROOT):
    """Newest MULTICHIP_r*.json whose captured tail carries the dryrun
    PARITY record (older captures were liveness-only).  Returns
    ``(name, parsed record or None)``."""
    recs = sorted(glob.glob(os.path.join(root, "MULTICHIP_r*.json")))
    for path in reversed(recs):
        try:
            with open(path) as fh:
                rec = json.load(fh)
        except ValueError:
            continue
        m = re.search(r"dryrun_multichip PARITY (\{.*\})",
                      rec.get("tail", ""))
        if m:
            try:
                return os.path.basename(path), json.loads(m.group(1))
            except ValueError:
                continue
    return (os.path.basename(recs[-1]) if recs else None), None


def comm_section(w, mc_name, mc):
    """Cross-chip comms: the analytic per-round byte table of every
    learner at the dryrun smoke shape (single source of truth:
    lightgbmv1_tpu.parallel.cluster.comm_table_per_round — the same
    function the trainer logs at build time and dryrun_multichip records),
    plus the measured-record guard when a MULTICHIP capture carries it."""
    try:
        if ROOT not in sys.path:
            sys.path.insert(0, ROOT)
        from lightgbmv1_tpu.parallel.cluster import comm_table_per_round
    except Exception as e:  # noqa: BLE001 — report generation must not die
        w(f"(comm table unavailable: {type(e).__name__})")
        w("")
        return
    w("## Cross-chip comms (per sustained wave round, analytic)")
    w("")
    w(f"Output-payload bytes per device per K={SMOKE['K']}-split round at "
      f"the dryrun smoke shape (D={SMOKE['ndev']}, F={SMOKE['F']}, "
      f"B={SMOKE['B']}; parallel/cluster.py comm_table_per_round — the "
      "trainer logs the same table at build time):")
    w("")
    w("| learner / collective | histogram | split sync | votes | total |")
    w("|---|---|---|---|---|")
    rows = (
        ("data / reduce_scatter", "data", "reduce_scatter", None),
        ("data / allreduce (parity pin)", "data", "allreduce", None),
        ("voting / reduce_scatter", "voting", "reduce_scatter",
         min(2 * SMOKE["top_k"], SMOKE["F"])),
        ("feature", "feature", "allreduce", None),
    )
    for label, learner, coll, sel_k in rows:
        t = comm_table_per_round(learner, coll, k=SMOKE["K"],
                                 F=SMOKE["F"], B=SMOKE["B"],
                                 ndev=SMOKE["ndev"], sel_k=sel_k)
        w(f"| {label} | {t['hist_bytes']} | {t['split_sync_bytes']} | "
          f"{t.get('vote_bytes', '—')} | {t['total_bytes']} |")
    w("")
    w("The reduce-scatter path keeps F/D features per chip and syncs only "
      "packed SplitInfo (the reference's ReduceScatter + "
      "SyncUpGlobalBestSplit mapping); int8sr rounds move raw int32 "
      "through the histogram collective (ops/quantize.py global scales).")
    w("")
    if mc and mc.get("comm_bytes_per_round"):
        w(f"Measured-record table (`{mc_name}`, replayed wave schedule, "
          f"mean-k rounds, D={mc.get('n_devices')}):")
        w("")
        w("| learner | histogram | split sync | total | dtype |")
        w("|---|---|---|---|---|")
        for name, t in mc["comm_bytes_per_round"].items():
            w(f"| {name} | {t.get('hist_bytes')} | "
              f"{t.get('split_sync_bytes')} | {t.get('total_bytes')} | "
              f"{t.get('hist_dtype')} |")
        w("")
        w(f"Comm guard `comm_ok={mc.get('comm_ok')}` (reduce-scatter "
          "histogram bytes must be <= allreduce / (D*0.9); "
          "cluster.comm_guard_ok — the dryrun asserts it, this report "
          "surfaces it).")
    else:
        w("No MULTICHIP capture with a PARITY record yet — the next "
          "driver run of tools/dryrun_multichip records the measured "
          "table and the `comm_ok` guard into the MULTICHIP record.")
    w("")


def pod_comm_section(w, mc_name, mc):
    """Pod-scale comms (ISSUE 16): the hierarchical ICI/DCN collective's
    per-level analytic wire table at the dryrun smoke shape (single
    source of truth: parallel/cluster.py hier_comm_table_per_round — the
    same function the trainer logs at build time and dryrun_multichip
    records), plus the measured-record guards when a MULTICHIP capture
    carries them.  Placeholder until then — the section never dies."""
    try:
        if ROOT not in sys.path:
            sys.path.insert(0, ROOT)
        from lightgbmv1_tpu.parallel.cluster import hier_comm_table_per_round
    except Exception as e:  # noqa: BLE001 — report generation must not die
        w(f"(hier comm table unavailable: {type(e).__name__})")
        w("")
        return
    H = 2
    w("## Pod-scale comms (hierarchical ICI/DCN collective, per wave "
      "round)")
    w("")
    w(f"Per-level ring SEND bytes per device per K={SMOKE['K']}-split "
      f"round at the dryrun smoke shape (D={SMOKE['ndev']} as H={H} "
      f"hosts x C={SMOKE['ndev'] // H} chips, F={SMOKE['F']}, "
      f"B={SMOKE['B']}; parallel/cluster.py hier_comm_table_per_round). "
      "`data_parallel_collective=hierarchical` reduce-scatters over the "
      "fast intra-host ICI axis FIRST, so only the F/D-sliced partials "
      "ever cross the slow inter-host DCN link; the voting learner's "
      "top-2k election additionally compresses WHAT crosses:")
    w("")
    w("| learner / level | histogram | split sync | votes | total |")
    w("|---|---|---|---|---|")
    tables = {}
    for learner in ("data", "voting"):
        t = hier_comm_table_per_round(
            learner, k=SMOKE["K"], F=SMOKE["F"], B=SMOKE["B"],
            ndev=SMOKE["ndev"], num_hosts=H,
            sel_k=(min(2 * SMOKE["top_k"], SMOKE["F"])
                   if learner == "voting" else None))
        tables[learner] = t
        for level in ("ici", "dcn"):
            lv = t[level]
            w(f"| {learner} / {level} | {lv['hist_bytes']} | "
              f"{lv['split_sync_bytes']} | {lv['vote_bytes']} | "
              f"{lv['total_bytes']} |")
        w(f"| {learner} / flat ring (all-DCN baseline) | "
          f"{t['flat_hist_wire_bytes']} | — | — | — |")
    w("")
    dt = tables["data"]
    w(f"Modeled round latency at the ICI/DCN bandwidth gap "
      f"(cluster.ICI_GBPS/DCN_GBPS): hierarchical "
      f"{fmt(dt['hier_ms'], 5)} ms vs flat {fmt(dt['flat_ms'], 5)} ms "
      f"for the data learner — the flat ring's slowest hop is a DCN "
      "hop, which is exactly why the hierarchy pays.")
    w("")
    if mc and mc.get("hier_comm_bytes_per_round"):
        w(f"Measured-record table (`{mc_name}`, "
          f"D={mc.get('n_devices')}, mean-k rounds):")
        w("")
        w("| learner | ICI hist | DCN hist | DCN total | flat wire |")
        w("|---|---|---|---|---|")
        for name, t in mc["hier_comm_bytes_per_round"].items():
            w(f"| {name} | {(t.get('ici') or {}).get('hist_bytes')} | "
              f"{(t.get('dcn') or {}).get('hist_bytes')} | "
              f"{(t.get('dcn') or {}).get('total_bytes')} | "
              f"{t.get('flat_hist_wire_bytes')} |")
        w("")
        wire = mc.get("hier_wire_measured") or {}
        w(f"Guards: `hier_comm_ok={mc.get('hier_comm_ok')}` (DCN "
          "histogram bytes <= flat reduce-scatter wire / num_hosts, the "
          "voting learner additionally within its top-2k analytic bound "
          "— cluster.hier_comm_ok, required by tools/ci_gate.py "
          "--require-guards) and `hier_measured_vs_analytic_ok="
          f"{mc.get('hier_measured_vs_analytic_ok')}` (the lowered "
          "StableHLO's reduce-scatter ops, split by replica-group size: "
          f"measured ICI/DCN wire ratio {get(wire, 'ici_dcn_ratio', 2)} "
          "vs analytic "
          f"{get(mc, 'hier_wire_analytic_ici_dcn_ratio', 2)}, within "
          "5%).")
    else:
        w("No MULTICHIP capture with hierarchical fields yet — the next "
          "driver run of tools/dryrun_multichip trains the "
          "data_hierarchical/voting_hierarchical parity set on the 2x4 "
          "virtual mesh and records the per-level table, the "
          "`hier_comm_ok` guard and the measured-vs-analytic wire "
          "ratio into the MULTICHIP record.")
    w("")


def fused_section(w, rec):
    """Fused wave-round megakernel (ISSUE 13 — ops/wave_fused.py,
    bench.py measure_fused / measure_fused_round_ms): parity, the merged
    hist+split round vs the staged phases it replaces, and the
    compiled-executable HBM accounting.  Placeholder until the first
    capture that carries the fields."""
    if rec.get("fused_parity_ok") is None and rec.get("fused_ok") is None:
        return
    w("## Fused wave round (hist_method=fused, ops/wave_fused.py)")
    w("")
    w(f"Tree parity vs the staged pallas path: "
      f"`fused_parity_ok={rec.get('fused_parity_ok')}`; throughput "
      f"{get(rec, 'fused_M_row_trees_per_s')} M row-trees/s vs staged "
      f"{get(rec, 'fused_staged_pallas_M_row_trees_per_s')}.")
    w("")
    if rec.get("hist_split_fused_ms_per_iter") is not None:
        w(f"Merged hist+split round: "
          f"**{get(rec, 'hist_split_fused_ms_per_iter')} ms/iter** "
          f"(replayed schedule, staged root pass included) vs staged "
          f"`phase_hist_ms + phase_split_ms` = "
          f"{get(rec, 'phase_hist_ms')} + {get(rec, 'phase_split_ms')} "
          "ms/iter.")
        w("")
    if rec.get("partition_fused_ms_per_iter") is not None:
        w(f"Single-pass round (ISSUE 15 — partition + valid routing + "
          f"top-k folded into the dispatch): "
          f"**{get(rec, 'partition_fused_ms_per_iter')} ms/iter** "
          f"(replayed schedule, staged root pass included) vs staged "
          f"`phase_hist_ms + phase_split_ms + phase_partition_ms` = "
          f"{get(rec, 'phase_hist_ms')} + {get(rec, 'phase_split_ms')} "
          f"+ {get(rec, 'phase_partition_ms')} ms/iter.")
        w("")
    if rec.get("fused_loop_parity_ok") is not None:
        w(f"Persistent multi-round wave loop (ISSUE 17 — "
          f"`wave_loop_rounds={get(rec, 'fused_loop_rounds')}`, frontier "
          f"state resident in VMEM across rounds): parity "
          f"`fused_loop_parity_ok={rec.get('fused_loop_parity_ok')}`; "
          f"{get(rec, 'wave_loop_ms_per_iter')} ms/iter looped vs "
          f"{get(rec, 'wave_loop_single_round_ms_per_iter')} single-round "
          f"({get(rec, 'fused_loop_launches_saved_per_segment')} launches "
          f"and {get(rec, 'fused_loop_state_bytes_saved_per_segment_analytic')} "
          f"state bytes saved per segment, analytic"
          + (f"; measured boundary saving "
             f"{get(rec, 'wave_loop_boundary_saving_ms_per_iter')} ms/iter"
             if rec.get("wave_loop_boundary_saving_ms_per_iter")
             is not None else "") + ").")
        w("")
    if rec.get("fused_hbm_bytes_saved_per_round") is not None:
        w(f"Compiled-executable HBM accounting (cost_analysis bytes, one "
          f"sustained-bucket round incl. the staged partition pass): "
          f"staged {get(rec, 'staged_round_bytes_accessed')} vs fused "
          f"{get(rec, 'fused_round_bytes_accessed')} — "
          f"**{get(rec, 'fused_hbm_bytes_saved_per_round')} bytes/round "
          f"saved** ({get(rec, 'fused_round_bytes_reduction', 3)}x; "
          f"analytic scan-stack size "
          f"{get(rec, 'fused_hbm_stack_bytes_analytic')}): the "
          "(F, B, 3) histogram stack stays in VMEM and the binned "
          f"matrix is read once per round (analytic binned traffic "
          f"{get(rec, 'fused_round_binned_bytes_analytic')} vs staged "
          f"{get(rec, 'staged_round_binned_bytes_analytic')} bytes).")
        w("")
    w(f"Guard `fused_ok={rec.get('fused_ok')}`: parity AND (on device) "
      "fused round <= staged hist+split.  Guard "
      f"`fused_round_ok={rec.get('fused_round_ok')}` (ISSUE 15): routed "
      "parity AND the binned-read-once bytes contract (>= 1.8x "
      "cost_analysis reduction vs staged partition+hist on device).  "
      f"Guard `fused_loop_ok={rec.get('fused_loop_ok')}` (ISSUE 17): "
      "loop-vs-single-round parity AND (on device) a non-negative "
      "boundary saving.  "
      "The staged path stays the default until a device capture lands "
      "these guards True "
      "(BASELINE.md \"Fused wave round\" / \"Persistent multi-round "
      "wave loop\" — dispatch rules, fallback taxonomy, parity "
      "contract).")
    w("")


def prediction_section(w, rec):
    """Prediction: the serving-engine table (native C++ / depth-stepped
    device walk / legacy scan pin) plus the component split of the device
    file->file window (parse / prebin / H2D / walk / write) and the
    ``predict_ok`` guard — every figure greps to a BENCH predict_* field
    (bench.py measure_predict).  Renders a placeholder until the first
    capture that carries the fields."""
    w("## Prediction (file->file on the bench set)")
    w("")
    if rec.get("predict_M_rows_per_s") is None:
        w("No predict fields in this record yet — the next driver capture "
          "runs bench.py's measure_predict (native C++ predictor, the "
          "depth-stepped all-trees device walk on prebinned serving "
          "codes, and the legacy scan-walk parity pin) and this section "
          "renders its parse/H2D/walk split and the `predict_ok` guard.")
        w("")
        return
    w(f"{get(rec, 'predict_n_trees', 0)} trees, "
      f"{get(rec, 'predict_rows', 0)} rows:")
    w("")
    w("| engine | M rows/s (file->file) | M rows/s (compute only) |")
    w("|---|---|---|")
    w(f"| native C++ predictor | {get(rec, 'predict_M_rows_per_s', 3)}"
      f" | {get(rec, 'predict_native_compute_M_rows_per_s', 3)} |")
    w(f"| device depth-stepped walk | "
      f"{get(rec, 'predict_device_M_rows_per_s', 3)} | "
      f"{get(rec, 'predict_device_compute_M_rows_per_s', 3)} |")
    if rec.get("predict_device_scan_M_rows_per_s") is not None:
        w(f"| device scan walk (parity pin) | — | "
          f"{get(rec, 'predict_device_scan_M_rows_per_s', 3)} |")
    if rec.get("predict_fused_M_rows_per_s") is not None:
        w(f"| fused megakernel (walk+accumulate) | — | "
          f"{get(rec, 'predict_fused_M_rows_per_s', 3)} |")
    if rec.get("predict_ref_cpp_M_rows_per_s"):
        w(f"| reference CLI task=predict | "
          f"{get(rec, 'predict_ref_cpp_M_rows_per_s', 3)} | — |")
    w("")
    if rec.get("predict_walk_ms") is not None:
        w("Device window components (ms, chunk-sized batch): parse "
          f"{get(rec, 'predict_parse_ms')} / prebin "
          f"{get(rec, 'predict_prebin_ms')} / H2D "
          f"{get(rec, 'predict_h2d_ms')} / walk "
          f"{get(rec, 'predict_walk_ms')} / write "
          f"{get(rec, 'predict_write_ms')}; "
          f"{get(rec, 'predict_h2d_bytes_per_row', 0)} H2D bytes/row "
          "(prebinned serving codes), "
          f"{get(rec, 'predict_cache_retraces', 0)} retraces across "
          "varied batch sizes (predictor cache).")
        w("")
    if rec.get("predict_h2d_bytes_per_row_packed") is not None:
        w("Serving megakernel transport: "
          f"{get(rec, 'predict_h2d_bytes_per_row_packed', 0)} H2D "
          "bytes/row with 4-bit packed serving codes "
          f"({get(rec, 'predict_packed_h2d_reduction')}x reduction vs "
          "the byte-wide twin, analytic ceil(F/2)); measured "
          "cost_analysis bytes "
          f"{get(rec, 'predict_fused_bytes_accessed', 0)} vs analytic "
          f"single-read floor {get(rec, 'predict_fused_bytes_analytic', 0)}"
          f"; {get(rec, 'predict_fused_cache_retraces', 0)} retraces "
          "across varied batch sizes through the fused dispatch.")
        w("")
    if rec.get("predict_ok") is not None:
        w(f"Guard `predict_ok={rec.get('predict_ok')}`: node-exact leaf "
          f"parity vs the host walk "
          f"(`predict_parity_ok={rec.get('predict_parity_ok')}`) AND the "
          "depth-stepped walk at >= 0.95x the scan-walk compute rate "
          "(bench.py asserts the split; this report surfaces it).")
        w("")
    if rec.get("predict_fused_ok") is not None:
        w(f"Guard `predict_fused_ok={rec.get('predict_fused_ok')}`: the "
          "fused walk+accumulate megakernel node/bit-exact vs the host "
          "oracle "
          f"(`predict_fused_parity_ok={rec.get('predict_fused_parity_ok')}"
          "`), zero retraces within a bucket, and on device >= 1.5x the "
          "scan walk's compute rate with cost_analysis bytes confirming "
          "the single-read contract.")
        w("")


def serving_section(w, rec):
    """Serving: the online-subsystem loadgen figures (serve/ — deadline-
    aware micro-batching, hot-swap registry, bounded-queue admission
    control) — every figure greps to a BENCH serve_* field written by
    bench.py's measure_serve via tools/loadgen.py.  Renders a placeholder
    until the first capture that carries the fields."""
    w("## Serving (open-loop loadgen against the in-process server)")
    w("")
    if rec.get("serve_qps") is None:
        w("No serve fields in this record yet — the next driver capture "
          "runs bench.py's measure_serve (tools/loadgen.py open-loop "
          "Poisson traffic with a mid-run hot-swap, then a bounded-queue "
          "overload probe) and this section renders the QPS / latency "
          "quantiles / batch occupancy / shed figures and the `serve_ok` "
          "guard.")
        w("")
        return
    w(f"{get(rec, 'serve_requests', 0)} requests at "
      f"{get(rec, 'serve_offered_qps', 1)} offered QPS "
      "(live phase, hot-swap mid-run):")
    w("")
    w("| achieved QPS | p50 ms | p99 ms | p999 ms | batch occupancy | "
      "shed frac |")
    w("|---|---|---|---|---|---|")
    w(f"| {get(rec, 'serve_qps', 1)} | {get(rec, 'serve_p50_ms', 3)} | "
      f"{get(rec, 'serve_p99_ms', 3)} | {get(rec, 'serve_p999_ms', 3)} | "
      f"{get(rec, 'serve_batch_occupancy', 4)} | "
      f"{get(rec, 'serve_shed_frac', 4)} |")
    w("")
    versions = rec.get("serve_versions") or {}
    if versions:
        served = ", ".join(f"{k}: {v}" for k, v in versions.items())
        w(f"Hot swap under live traffic: versions served {{{served}}} "
          f"across {get(rec, 'serve_swap_count', 0)} publishes — every "
          "response bit-identical to `Booster.predict` of the version "
          "tag it carries (checked per request by the loadgen).")
        w("")
    if rec.get("serve_overload_shed_frac") is not None:
        w(f"Overload probe (2x+ capacity into a "
          f"{get(rec, 'serve_overload_queue_max', 0)}-row-max queue): "
          f"shed frac {get(rec, 'serve_overload_shed_frac', 4)} with the "
          "backlog bounded at the configured admission depth "
          f"(`serve_overload_queue_ok="
          f"{rec.get('serve_overload_queue_ok')}`) — explicit rejection, "
          "never unbounded growth.")
        w("")
    if rec.get("serve_ok") is not None:
        w(f"Guard `serve_ok={rec.get('serve_ok')}`: zero "
          "failed/incorrect responses in the live phase AND both "
          "versions served across the swap AND the overload queue "
          "stayed bounded (bench.py asserts the split; this report "
          "surfaces it).")
        w("")


def streaming_section(w, rec):
    """Streaming: the out-of-core block-cache trainer record (PR 8 —
    bench.py measure_stream, data/ subsystem).  Every figure greps to a
    BENCH stream_* field; placeholder until the first capture carrying
    them."""
    w("## Streaming (out-of-core row-block training, data/ block cache)")
    w("")
    if rec.get("stream_ok") is None:
        w("No stream fields in this record yet — the next driver capture "
          "runs bench.py's measure_stream (sharded block cache written "
          "once, row-block streaming trainer vs the resident trainer at "
          "the same sequential schedule) and this section renders the "
          "per-iteration clocks, the ledger-accounted peak device bytes "
          "against the O(stream_block_rows · F) bound, and the "
          "`stream_ok` guard (byte-identical model text AND bounded "
          "memory).")
        w("")
        return
    w(f"{get(rec, 'stream_rows', 0)} rows streamed in "
      f"{get(rec, 'stream_block_rows', 0)}-row blocks:")
    w("")
    w("| stream ms/iter | resident ms/iter | ratio | peak device bytes | "
      "bound | resident matrix bytes |")
    w("|---|---|---|---|---|---|")
    w(f"| {get(rec, 'stream_ms_per_iter', 2)} | "
      f"{get(rec, 'stream_resident_ms_per_iter', 2)} | "
      f"{get(rec, 'stream_vs_resident_ratio', 3)} | "
      f"{get(rec, 'stream_peak_device_bytes', 0)} | "
      f"{get(rec, 'stream_peak_device_bound_bytes', 0)} | "
      f"{get(rec, 'stream_resident_matrix_bytes', 0)} |")
    w("")
    w(f"Guard `stream_ok={rec.get('stream_ok')}`: model text "
      f"byte-identical to the resident trainer "
      f"(`stream_parity_ok={rec.get('stream_parity_ok')}` — the fixed-"
      "block-order parity contract, BASELINE.md) AND ledger-accounted "
      "peak device bytes within the analytic block-scaled bound "
      f"(`stream_mem_ok={rec.get('stream_mem_ok')}`): the device "
      "working set scales with `stream_block_rows`, not dataset rows.")
    w("")


def robustness_section(w, rec):
    """Robustness: the scripted chaos-suite record (PR 6 — bench.py
    measure_chaos via tools/chaos.py).  Each row is one injected-fault
    scenario and whether its recovery path held; ``chaos_ok`` is the
    all-scenarios guard.  Renders a placeholder until the first capture
    that carries the fields."""
    w("## Robustness (scripted fault injection, tools/chaos.py)")
    w("")
    if rec.get("chaos_ok") is None:
        w("No chaos fields in this record yet — the next driver capture "
          "runs bench.py's measure_chaos (the fast deterministic subset "
          "of tools/chaos.py: kill-and-resume with bit-identical model "
          "text, torn-checkpoint fallback, NaN-poisoned gradients, "
          "publish-of-garbage, dispatcher stall/death, bounded-queue "
          "overload, transient-H2D retry) and this section renders the "
          "per-scenario table and the `chaos_ok` guard.")
        w("")
        return
    scenarios = rec.get("chaos_scenarios") or {}
    w(f"{get(rec, 'chaos_n_scenarios', 0)} scripted fault scenarios"
      + (f" in {get(rec, 'chaos_seconds', 1)} s"
         if rec.get("chaos_seconds") is not None else "") + ":")
    w("")
    w("| scenario | recovered |")
    w("|---|---|")
    labels = {
        "train_kill_resume": "kill mid-training -> checkpoint auto-resume "
                             "(bit-identical model text)",
        "torn_snapshot": "torn newest checkpoint -> validated fallback to "
                         "previous intact bundle",
        "poisoned_gradients": "NaN-poisoned gradient pass -> finite_guard "
                              "detect (raise) + survive (clamp)",
        "publish_of_garbage": "corrupt model publish -> rejected pre-swap, "
                              "never serves an answer",
        "dispatcher_stall": "stalled/dead dispatcher -> watchdog 503 + "
                            "thread restart",
        "overload": "burst over capacity -> explicit shed, bounded queue",
        "h2d_transient": "transient H2D failure -> bounded "
                         "retry-with-backoff, zero client errors",
    }
    for name, ok in scenarios.items():
        w(f"| {labels.get(name, name)} | {ok} |")
    w("")
    w(f"Guard `chaos_ok={rec.get('chaos_ok')}`: EVERY injected fault "
      "recovered (bench.py runs the suite on every backend; "
      "__graft_entry__.chaos_smoke hard-asserts it each driver "
      "capture).  Knobs: `finite_guard=off|warn|raise|clamp` on the "
      "gradient pass; `serve_retry_max`/`serve_breaker_failures`/"
      "`serve_watchdog_ms`/`serve_probe_rows` on the serving failure "
      "domains (BASELINE.md).")
    w("")


def observability_section(w, rec):
    """Observability: the obs/ subsystem's own cost and validity record
    (ISSUE 9 — bench.py measure_obs): armed-tracer overhead vs the
    2% contract, off-path bit-parity, trace validity for the train and
    serve paths, and Prometheus exposition health.  Placeholder until
    the first capture that carries the fields."""
    w("## Observability (span tracer + metrics registry, obs/)")
    w("")
    if rec.get("obs_ok") is None:
        w("No obs fields in this record yet — the next driver capture "
          "runs bench.py's measure_obs (A/B train with the span tracer "
          "armed vs off, a traced serve loadgen window, and a Prometheus "
          "exposition probe) and this section renders the overhead "
          "fraction against the 2% contract and the `obs_ok` guard.")
        w("")
        return
    w("| armed overhead frac | span cover of train wall | trace events | "
      "off-path parity | prom exposition |")
    w("|---|---|---|---|---|")
    w(f"| {get(rec, 'obs_overhead_frac', 4)} | "
      f"{get(rec, 'obs_span_cover_frac', 4)} | "
      f"{get(rec, 'obs_trace_events', 0)} | "
      f"{rec.get('obs_parity_ok')} | {rec.get('obs_prom_ok')} |")
    w("")
    w(f"Guard `obs_ok={rec.get('obs_ok')}`: armed tracing costs <= 2% of "
      "train wall AND the disarmed run's model text is byte-identical "
      f"(`obs_parity_ok={rec.get('obs_parity_ok')}`) AND both exported "
      "Chrome traces are valid with train iteration spans covering the "
      "measured wall within 10% "
      f"(`obs_trace_ok={rec.get('obs_trace_ok')}`) and serve request "
      "spans decomposing queue/walk "
      f"(`obs_serve_trace_ok={rec.get('obs_serve_trace_ok')}`).  Knobs: "
      "`obs_trace`, `trace_out`, `obs_ring_events` (BASELINE.md); "
      "`GET /metrics` serves Prometheus text under content negotiation.")
    w("")


def device_truth_section(w, rec):
    """Device truth (ISSUE 12 — bench.py measure_obs's device block +
    obs/xla.py): compile telemetry (labeled compile walls, retrace
    counters, the serving zero-retrace probe), HBM footprint vs the
    streaming ledger, and the per-phase roofline join.  Placeholder
    until the first capture that carries the fields."""
    w("## Device truth (compile/memory/cost telemetry, obs/xla.py)")
    w("")
    if rec.get("obs_device_ok") is None:
        w("No device-truth fields in this record yet — the next driver "
          "capture runs the extended measure_obs (labeled lower/compile "
          "telemetry on the trainer dispatches, predictor cache and "
          "parallel learners; a serving-bucket zero-retrace probe; "
          "device.memory_stats() reconciled against the streaming "
          "DeviceLedger; the per-phase roofline join) and this section "
          "renders `compile_ms_total`, the retrace counters, "
          "`hbm_peak_bytes`/`ledger_agreement` and the `obs_device_ok` "
          "guard.  `tools/capture.py` is the one-command driver that "
          "produces it.")
        w("")
        return
    w("| compile ms (total) | serve bucket retraces | HBM peak bytes | "
      "ledger agreement |")
    w("|---|---|---|---|")
    w(f"| {get(rec, 'compile_ms_total', 1)} | "
      f"{get(rec, 'serve_bucket_retraces', 0)} | "
      f"{get(rec, 'hbm_peak_bytes', 0)} | "
      f"{get(rec, 'ledger_agreement', 4)} |")
    w("")
    counts = rec.get("compile_counts") or {}
    retraces = rec.get("retrace_counts") or {}
    if counts:
        w("Per-label compiles (retraces): "
          + ", ".join(f"{k} {counts[k]} ({retraces.get(k, 0)})"
                      for k in sorted(counts)) + ".")
        w("")
    if rec.get("train_step_flops") is not None:
        w(f"Compiled train step cost analysis: "
          f"{get(rec, 'train_step_flops', 0)} flops, "
          f"{get(rec, 'train_step_bytes_accessed', 0)} bytes accessed, "
          f"{get(rec, 'train_step_temp_bytes', 0)} temp bytes "
          "(the compiled executable's own cost/memory analysis — "
          "obs/xla.py records it at every labeled compile).")
        w("")
    rl = rec.get("phase_roofline") or {}
    if rl:
        w("Per-phase roofline (measured phase ms x cost-analysis split "
          "vs the same-session matmul peak; "
          "tools/phase_attrib.roofline_attribution):")
        w("")
        w("| phase | ms | achieved TF/s | frac of peak | bound |")
        w("|---|---|---|---|---|")
        for phase in sorted(rl):
            row = rl[phase]
            w(f"| {phase} | {fmt(row.get('ms'))} | "
              f"{fmt(row.get('achieved_tf_s'), 4)} | "
              f"{fmt(row.get('frac_of_peak'), 4)} | "
              f"{row.get('bound', '—')} |")
        w("")
    w(f"Guard `obs_device_ok={rec.get('obs_device_ok')}`: compile "
      "telemetry present for the training dispatches AND zero serving "
      "bucket retraces AND (when the backend reports allocator stats) a "
      "positive HBM peak with the ledger agreement in (0, 1.5].  "
      "`tools/bench_trend.py` watches `compile_ms_total` (generous 50% "
      "bar — compile time is noisy) and `hbm_peak_bytes` (10%).")
    w("")


def forensics_slo_section(w, rec):
    """Forensics & SLO (ISSUE 10 — bench.py measure_obs + measure_chaos):
    the serving SLO burn-rate block (availability / latency SLIs,
    exemplar trace ids), the flight-recorder drill, the loadgen+server
    aggregation probe, and the chaos suite's bundle contract.
    Placeholder until the first capture that carries the fields."""
    w("## Forensics & SLO (flight recorder + burn-rate, obs/dump.py + "
      "serve/slo.py)")
    w("")
    if rec.get("slo_ok") is None and rec.get("forensics_ok") is None:
        w("No forensics/SLO fields in this record yet — the next driver "
          "capture runs the extended measure_obs (SLO burn-rate "
          "evaluation over the loadgen window with exemplar trace ids, "
          "a flight-recorder drill writing one validated bundle, and "
          "the loadgen+server artifact aggregation probe) plus "
          "measure_chaos's per-scenario bundle contract, and this "
          "section renders the `slo_ok` / `forensics_ok` / "
          "`obs_agg_ok` / `chaos_forensics_ok` guards.")
        w("")
        return
    w("| availability SLI (fast) | latency SLI (fast) | avail burn | "
      "exemplars | agg sources |")
    w("|---|---|---|---|---|")
    w(f"| {get(rec, 'slo_availability', 4)} | "
      f"{get(rec, 'slo_latency_sli', 4)} | "
      f"{get(rec, 'slo_availability_burn', 4)} | "
      f"{get(rec, 'slo_exemplars', 0)} | "
      f"{get(rec, 'obs_agg_sources', 0)} |")
    w("")
    w(f"Guards: `slo_ok={rec.get('slo_ok')}` (sane multi-window "
      "burn-rate evaluation, page-on-burning/quiet-on-clean alert "
      "logic, 16-hex exemplar trace ids on the latency buckets, "
      "`GET /slo` payload serializes); "
      f"`forensics_ok={rec.get('forensics_ok')}` (an armed flight "
      "recorder writes exactly ONE schema-valid, digest-intact, "
      "Perfetto-loadable bundle per arming); "
      f"`obs_agg_ok={rec.get('obs_agg_ok')}` (tools/obs_aggregate.py "
      "merges the loadgen + server artifacts into one trace with "
      "distinct pid lanes and one additive snapshot); "
      f"`chaos_forensics_ok={rec.get('chaos_forensics_ok')}` (every "
      "chaos kill/wedge left exactly one validated bundle, every "
      "recovered fault left none).  Knobs: `crash_dir` / "
      "`LGBMV1_CRASH_DIR`, `obs_dir` / `LGBMV1_OBS_DIR`, "
      "`serve_slo_*` (BASELINE.md).")
    w("")


def model_quality_section(w, rec):
    """Model quality & drift (ISSUE 14 — bench.py measure_drift +
    obs/model.py + obs/drift.py): the trainer quality telemetry summary
    and the serving-side skew-injection probe (clean traffic quiet,
    injected shift detected, streamed-vs-resident reference byte
    parity, armed-sampling overhead vs the <= 2% contract).
    Placeholder until the first capture that carries the fields."""
    w("## Model quality & drift (reference capture + skew detection, "
      "obs/model.py + obs/drift.py)")
    w("")
    if rec.get("drift_ok") is None:
        w("No model-quality fields in this record yet — the next driver "
          "capture runs bench.py's measure_drift (deterministic "
          "skew-injection probe against a drift-armed server, the "
          "streamed-vs-resident reference byte-parity check, the armed "
          "sampling overhead A/B, and the trainer quality telemetry "
          "summary) and this section renders the injected/clean PSI, "
          "the split-gain and tree-shape aggregates, and the `drift_ok` "
          "guard.")
        w("")
        return
    w("| injected PSI | clean PSI max | clean false alarms | "
      "overhead frac | stream ref parity |")
    w("|---|---|---|---|---|")
    w(f"| {get(rec, 'drift_injected_psi', 4)} | "
      f"{get(rec, 'drift_clean_psi_max', 4)} | "
      f"{get(rec, 'drift_clean_false_alarms', 0)} | "
      f"{get(rec, 'drift_overhead_frac', 4)} | "
      f"{rec.get('drift_ref_stream_parity_ok')} |")
    w("")
    top = rec.get("train_top_gain_features") or []
    w(f"Trainer quality telemetry: split gain p50 "
      f"{get(rec, 'train_split_gain_p50')} / p90 "
      f"{get(rec, 'train_split_gain_p90')}, mean "
      f"{get(rec, 'train_tree_leaves_mean')} leaves / depth "
      f"{get(rec, 'train_tree_depth_mean')} per tree"
      + (f"; top gain features: {', '.join(top)}" if top else "")
      + ".")
    w("")
    w(f"Guard `drift_ok={rec.get('drift_ok')}`: the +3-sigma "
      "skew-injection probe is DETECTED (injected feature alerts, "
      "ranks top-1, publishes a `drift.alert` event) AND clean traffic "
      "raises zero false alarms AND the serialized training reference "
      "is byte-identical between the resident and streaming trainers "
      "AND armed sampling stays within the <= 2% serving contract "
      f"(`drift_overhead_frac={get(rec, 'drift_overhead_frac', 4)}`).  "
      "Knobs: `drift_sample_rows` (hard-off default 0), "
      "`drift_psi_threshold`, `drift_top_k`, `drift_sample_stride` "
      "(BASELINE.md); `GET /drift` serves the evaluation.")
    w("")


def fleet_section(w, rec):
    """Fault-tolerant fleet (ISSUE 11 — bench.py measure_fleet): the
    replica-kill-under-loadgen drill (zero client-visible errors,
    router hedge rate, health-check ejection), the coordinated
    two-phase publish, and the elastic training kill-resume byte-parity
    drill with its recovery clock.  Placeholder until the first capture
    that carries the fields."""
    w("## Fleet (elastic recovery + self-healing serving, "
      "parallel/elastic.py + serve/router.py)")
    w("")
    if rec.get("fleet_ok") is None:
        w("No fleet fields in this record yet — the next driver capture "
          "runs bench.py's measure_fleet (a 3-replica fleet behind the "
          "self-healing router with one replica killed under open-loop "
          "loadgen, a coordinated two-phase publish onto the degraded "
          "fleet, and an elastic-coordinator training run killed at "
          "iteration 3 and re-bootstrapped from its checkpoint bundle) "
          "and this section renders the zero-error/ejection/parity "
          "guards, `router_hedge_frac` and `fleet_recovery_s`.")
        w("")
        return
    w("| requests | qps | p99 ms | hedge frac | router retries | "
      "recovery s | elastic world |")
    w("|---|---|---|---|---|---|---|")
    w(f"| {get(rec, 'fleet_requests', 0)} | {get(rec, 'fleet_qps', 1)} | "
      f"{get(rec, 'fleet_p99_ms', 2)} | "
      f"{get(rec, 'router_hedge_frac', 4)} | "
      f"{get(rec, 'fleet_router_retries', 0)} | "
      f"{get(rec, 'fleet_recovery_s', 2)} | "
      f"{get(rec, 'fleet_elastic_world', 0)} |")
    w("")
    w(f"Guard `fleet_ok={rec.get('fleet_ok')}`: replica killed "
      "mid-loadgen with ZERO client-visible errors "
      f"(`fleet_zero_error_ok={rec.get('fleet_zero_error_ok')}`), the "
      "dead replica health-check ejected "
      f"(`fleet_replica_ejected_ok={rec.get('fleet_replica_ejected_ok')}"
      "`), a two-phase publish landing one aligned tag fleet-wide "
      f"(`fleet_publish_ok={rec.get('fleet_publish_ok')}`), and the "
      "elastic kill-at-k run resuming to BYTE-IDENTICAL model text "
      f"(`fleet_kill_resume_ok={rec.get('fleet_kill_resume_ok')}`).  "
      "The chaos suite's fleet subset rides `chaos_fleet_ok="
      f"{rec.get('chaos_fleet_ok')}`.  Knobs: `serve_replicas`, "
      "`router_*` (hedge/retry/health), `elastic_*` (lease timeout, "
      "max restarts) — BASELINE.md \"Fault-tolerant fleet\".")
    w("")


def tenants_section(w, rec):
    """Multi-tenant serving (ISSUE 20 — bench.py measure_tenants): the
    compile-bucket-sharing counters, the fair-share isolation probe,
    per-tenant publish/rollback parity and the placement-move drill.
    Placeholder until the first capture that carries the fields."""
    w("## Multi-tenant serving (serve/tenants.py + serve/placement.py)")
    w("")
    if rec.get("tenant_ok") is None:
        w("No tenant fields in this record yet — the next driver "
          "capture runs bench.py's measure_tenants (two same-shape "
          "tenants sharing ONE compiled executable proven by per-label "
          "compile counters, a 2x hot-tenant overload with the cold "
          "tenant's p99 held inside its SLO, per-tenant "
          "publish/rollback bit-parity, and a burn-rate-triggered "
          "placement move) and this section renders "
          "`tenant_compile_share_frac`, the isolation p99 tax and the "
          "four probe guards.")
        w("")
        return
    w("| share frac | cache hits | 2nd-warm compiles | mixed retraces "
      "| hot sheds | cold sheds | cold p99 ms | isolation Δp99 ms | "
      "placement moves |")
    w("|---|---|---|---|---|---|---|---|---|")
    w(f"| {get(rec, 'tenant_compile_share_frac', 4)} | "
      f"{get(rec, 'tenant_shared_cache_hits', 0)} | "
      f"{get(rec, 'tenant_second_warm_compiles', 0)} | "
      f"{get(rec, 'tenant_mixed_retraces', 0)} | "
      f"{get(rec, 'tenant_hot_shed', 0)} | "
      f"{get(rec, 'tenant_cold_shed', 0)} | "
      f"{get(rec, 'tenant_cold_p99_ms', 2)} | "
      f"{get(rec, 'tenant_isolation_p99_delta_ms', 2)} | "
      f"{get(rec, 'tenant_placement_moves', 0)} |")
    w("")
    w(f"Guard `tenant_ok={rec.get('tenant_ok')}`: the second tenant's "
      "warm adopted the first tenant's executables — zero new "
      "per-label compiles, zero retraces under mixed-tenant traffic "
      f"(`tenant_compile_share_ok={rec.get('tenant_compile_share_ok')}"
      "`); the hot tenant shed its OWN traffic while the cold tenant "
      "kept zero sheds and a p99 inside its SLO bound "
      f"(`tenant_fair_share_ok={rec.get('tenant_fair_share_ok')}`); "
      "publishing v2 into tenant A left tenant B bit-identical and "
      "A's rollback restored v1 bit-exactly "
      f"(`tenant_publish_parity_ok={rec.get('tenant_publish_parity_ok')}"
      "`); the burn-rate signal moved the hot tenant with a fully "
      "attributed `placement.move` event "
      f"(`tenant_placement_move_ok={rec.get('tenant_placement_move_ok')}"
      "`).  Knobs: `tenant_manifest`, `registry_keep_versions`, "
      "`placement_*` — BASELINE.md \"Multi-tenant serving\".")
    w("")


def trend_section(w, root=ROOT):
    """Trend: the regression sentinel's view of the whole BENCH record
    trajectory (tools/bench_trend.py — the same comparator that gates
    captures renders this table, so PERF.md and the gate cannot
    disagree)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import bench_trend
    except Exception as e:  # noqa: BLE001 — report generation must not die
        w(f"(trend unavailable: {type(e).__name__})")
        w("")
        return
    result = bench_trend.run(root)
    w("## Trend (tools/bench_trend.py over every captured record)")
    w("")
    names = result["bench_records"]
    w(f"{len(names)} BENCH records "
      f"({names[0] if names else '—'} → {names[-1] if names else '—'}), "
      f"{len(result['multichip_records'])} MULTICHIP PARITY records.  "
      "Newest-record bars: watched fields within tolerance of the best "
      "prior capture, every `*_ok` guard True — the same check "
      "`tools/ci_gate.py` gates on.")
    w("")
    w("| field | newest | best prior | record | verdict |")
    w("|---|---|---|---|---|")
    for row in result["trend_rows"]:
        verdict = "**REGRESSED**" if row["regressed"] else "ok"
        prior = (f"{fmt(row['best_prior'], 4)} "
                 f"({row['best_prior_record']})"
                 if row["best_prior"] is not None else "first capture")
        w(f"| {row['field']} | {fmt(row['current'], 4)} | {prior} | "
          f"{row['record']} | {verdict} |")
    for f in result["flags"]:
        if f["kind"] != "regression":
            w(f"| {f['field']} | False | — | {f['record']} | "
              f"**{f['kind'].upper()}** |")
    w("")
    w(f"Sentinel verdict: {'OK' if result['ok'] else 'FLAGGED'} "
      "(`python tools/bench_trend.py` exits non-zero on any flag).")
    w("")


def fmt(v, nd=2):
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:.{nd}f}".rstrip("0").rstrip(".") if nd else f"{v:g}"
    return str(v)


def get(d, k, nd=2):
    return fmt(d.get(k), nd)


def generate(rec, name, prev=None, prev_name=None):
    L = []
    w = L.append
    w(f"# Performance record — generated by tools/perf_report.py "
      f"from `{name}`")
    w("")
    w("Every number below is a field of the captured record (grep the "
      "JSON); regenerate after each driver capture.  Mechanisms behind "
      "the numbers are documented where they live: ops/hist_pallas.py "
      "(kernel + precision modes), ops/quantize.py (int8sr stochastic "
      "rounding), models/grower_wave.py (wave schedule, slot buckets, "
      "quantized-round gate), tools/phase_attrib.py (residual "
      "attribution), and the git history.")
    w("")

    w(f"## Headline — {rec.get('metric', 'training throughput')}")
    w("")
    w("| | M row-trees/s | vs same-host ref C++ | vs published baseline |"
      " held-out AUC |")
    w("|---|---|---|---|---|")
    w(f"| reference C++ (this host, 1 core) | "
      f"{get(rec, 'ref_cpp_same_host_M_row_trees_per_s', 3)} | 1.00x | — | "
      f"{get(rec, 'auc_ref_lightgbm_cpp', 6)} |")
    w(f"| **leaf-wise (headline)** | **{get(rec, 'value', 3)}** | "
      f"**{get(rec, 'vs_ref_same_host', 4)}x** | "
      f"**{get(rec, 'vs_baseline', 4)}** | {get(rec, 'auc', 5)} "
      f"@{get(rec, 'auc_iters', 0)} iters |")
    w(f"| level-wise | {get(rec, 'levelwise_M_row_trees_per_s', 3)} | "
      f"{get(rec, 'levelwise_vs_ref_same_host', 4)}x | — | "
      f"{get(rec, 'levelwise_auc', 5)} |")
    if rec.get("dart_M_row_trees_per_s") is not None:
        w(f"| DART (per-iter dispatch) | "
          f"{get(rec, 'dart_M_row_trees_per_s', 3)} | — | — | — "
          f"({get(rec, 'dart_frac_of_scanned_gbdt', 3)} of scanned "
          f"leaf-wise) |")
    for k, label in (("goss", "GOSS (fused scan)"),
                     ("rf", "RF (fused scan)")):
        if rec.get(f"{k}_M_row_trees_per_s") is not None:
            w(f"| {label} | {get(rec, f'{k}_M_row_trees_per_s', 3)} | — | "
              f"— | — |")
    w("")
    w("`vs_baseline` divides by the published dual-Xeon HIGGS bar "
      "(40.36 M row-trees/s, docs/Experiments.rst:110-124); "
      "`vs_ref_same_host` by the reference C++ binary on THIS host — the "
      "like-for-like comparison.  The bench set is HIGGS-shaped "
      "synthetic (real HIGGS is not downloadable in this zero-egress "
      "environment).")
    w("")

    if rec.get("tpu_500iter_wall_s") is not None:
        w("## 500-tree north star (docs/Experiments.rst methodology)")
        w("")
        w("| | wall (500 trees) | valid AUC @500 |")
        w("|---|---|---|")
        w(f"| reference C++ (1 core) | "
          f"{get(rec, 'ref_cpp_500iter_wall_s')} s | "
          f"{get(rec, 'ref_cpp_500iter_auc', 6)} |")
        w(f"| **this repo** | **{get(rec, 'tpu_500iter_wall_s')} s** | "
          f"**{get(rec, 'tpu_500iter_auc', 6)}** |")
        w("")
        w(f"**{get(rec, 'vs_ref_500iter', 4)}x the reference** — the "
          "single-dispatch-amortized wall is the stable instrument (the "
          "tunnel drifts short windows up to ~2x; see "
          "`train_seconds_for_timed_block` vs the phase totals below).")
        w("")

    if rec.get("phase_hist_ms") is not None:
        w("## Per-phase breakdown (ms per leaf-wise iteration)")
        w("")
        if rec.get("partition_fused_ms_per_iter") is not None:
            # single-pass wave round (ISSUE 15): the routed round —
            # partition + valid routing + top-k folded into the fused
            # dispatch — next to the merged hist+split kernel and the
            # staged phases they replace
            w("| hist | partition | valid-route | split | other | "
              "measured total | hist+split fused | round fused |")
            w("|---|---|---|---|---|---|---|---|")
            w(f"| {get(rec, 'phase_hist_ms')} | "
              f"{get(rec, 'phase_partition_ms')} | "
              f"{get(rec, 'phase_valid_route_ms')} | "
              f"{get(rec, 'phase_split_ms')} | "
              f"{get(rec, 'phase_other_ms')} | "
              f"{get(rec, 'phase_total_measured_ms')} | "
              f"{get(rec, 'hist_split_fused_ms_per_iter')} | "
              f"**{get(rec, 'partition_fused_ms_per_iter')}** |")
        elif rec.get("hist_split_fused_ms_per_iter") is not None:
            # fused wave-round row (ISSUE 13): the merged hist+split
            # kernel next to the staged phases it replaces
            w("| hist | partition | valid-route | split | other | "
              "measured total | hist+split fused |")
            w("|---|---|---|---|---|---|---|")
            w(f"| {get(rec, 'phase_hist_ms')} | "
              f"{get(rec, 'phase_partition_ms')} | "
              f"{get(rec, 'phase_valid_route_ms')} | "
              f"{get(rec, 'phase_split_ms')} | "
              f"{get(rec, 'phase_other_ms')} | "
              f"{get(rec, 'phase_total_measured_ms')} | "
              f"**{get(rec, 'hist_split_fused_ms_per_iter')}** |")
        else:
            w("| hist | partition | valid-route | split | other | "
              "measured total |")
            w("|---|---|---|---|---|---|")
            w(f"| {get(rec, 'phase_hist_ms')} | "
              f"{get(rec, 'phase_partition_ms')} | "
              f"{get(rec, 'phase_valid_route_ms')} | "
              f"{get(rec, 'phase_split_ms')} | "
              f"{get(rec, 'phase_other_ms')} | "
              f"{get(rec, 'phase_total_measured_ms')} |")
        w("")
        tot = rec.get("phase_total_measured_ms") or 0
        hist = rec.get("phase_hist_ms") or 0
        if tot:
            w(f"Histogram work is ~{100 * hist / tot:.0f}% of the "
              f"iteration at `wave_rounds_per_tree` = "
              f"{get(rec, 'wave_rounds_per_tree')} (replayed schedule; "
              "sustained rounds priced at the deep dtype they actually "
              "run).")
        bd = rec.get("phase_other_breakdown")
        if bd:
            w("")
            w("`phase_other_ms` attribution (tools/phase_attrib.py): "
              + ", ".join(f"{k} {fmt(v)}" for k, v in bd.items())
              + f"; unattributed {get(rec, 'phase_other_unattributed_ms')}"
              f" (ok={rec.get('phase_attrib_ok')}).")
        sbd = rec.get("phase_split_breakdown")
        if sbd:
            w("")
            w("`phase_split_ms` sub-phases (ops/split.py fused scan — "
              "cumsum+missing-adjust / stacked gain eval / tie-band pick; "
              "tools/phase_attrib.py): "
              + ", ".join(f"{k} {fmt(v)}" for k, v in sbd.items())
              + f"; remainder {get(rec, 'phase_split_unattributed_ms')} "
              "(vmap plumbing + result assembly).")
        w("")

    if rec.get("pipeline_ok") is not None:
        w("## Wave pipelining (async_wave_pipeline A/B)")
        w("")
        w(f"Pipelined {get(rec, 'pipeline_ms_per_iter')} ms/iter vs "
          f"serialized legacy body "
          f"{get(rec, 'pipeline_serialized_ms_per_iter')} ms/iter — "
          f"overlap {get(rec, 'pipeline_overlap_ms')} ms/iter recovered "
          f"(`pipeline_ok={rec.get('pipeline_ok')}`: the overlapped "
          "per-iter total must not exceed the serialized sum; trivially "
          "true on CPU captures, where the backend serializes "
          "everything).  The pipelined schedule defers each round's "
          "histogram-state scatter and valid-row routing into the next "
          "round's computation (models/grower_wave.py) — bit-parity "
          "against the serialized body is pinned in "
          "tests/test_wave_pipeline.py.")
        w("")

    w("## Histogram kernel (bench config, measured same-session)")
    w("")
    w("| pass | ms |")
    w("|---|---|")
    for k, label in (
            ("hist_ms_per_pass", "bf16x2 full pass (K slots)"),
            ("hist_ms_per_pass_deep", "deep pass as trained (policy dtype)"),
            ("hist_ms_per_pass_int8sr", "int8sr quantized pass (K slots)"),
            ("hist_ms_per_pass_s16_int8sr", "int8sr quantized pass (16)"),
            ("hist_ms_per_pass_s16", "16-slot ramp bucket"),
            ("hist_ms_per_pass_s4", "4-slot ramp bucket"),
            ("hist_ms_per_pass_root", "root (1-slot) pass"),
    ):
        if rec.get(k) is not None:
            w(f"| {label} | {get(rec, k)} |")
    w("")
    w(f"Roofline: {get(rec, 'hist_achieved_tf_s')} TF/s achieved vs "
      f"{get(rec, 'device_matmul_peak_tf_s')} TF/s same-session matmul "
      f"peak = **{get(rec, 'hist_roofline_frac', 4)}** fraction "
      f"(`hist_ms_per_iter` {get(rec, 'hist_ms_per_iter')} over the "
      "replayed round schedule).")
    pe = (rec.get("precision_expt") or {}).get("deep_int8sr")
    if pe:
        w("")
        w("int8sr AUC-parity experiment (the `hist_dtype_deep=auto` flip "
          f"gate): auc {fmt(pe.get('auc'), 5)} vs default "
          f"{get(rec, 'auc', 5)} at {fmt(pe.get('auc_iters'), 0)} iters "
          f"(delta {fmt(pe.get('auc_delta_vs_default'), 6)}, "
          f"auc_parity={pe.get('auc_parity')}), "
          f"{fmt(pe.get('M_row_trees_per_s'), 3)} M row-trees/s, "
          f"quantized buckets active: {pe.get('quant_buckets_active')} "
          "(empty = the shape never reached the quantized gate — the "
          "flip needs a device capture where it engages).")
    if prev is not None and prev.get("hist_roofline_frac") is not None:
        w("")
        w(f"Cross-record note ({prev_name} -> {name}): "
          f"`hist_roofline_frac` {get(prev, 'hist_roofline_frac', 4)} -> "
          f"{get(rec, 'hist_roofline_frac', 4)} is mostly DENOMINATOR "
          f"drift — `device_matmul_peak_tf_s` moved "
          f"{get(prev, 'device_matmul_peak_tf_s')} -> "
          f"{get(rec, 'device_matmul_peak_tf_s')} between captures (the "
          f"same tunnel drift the throughput ranges carry), while the "
          f"achieved pass moved "
          f"{get(prev, 'hist_achieved_tf_s')} -> "
          f"{get(rec, 'hist_achieved_tf_s')} TF/s — not a kernel "
          "regression.")
    w("")

    if rec.get("multiclass_M_row_trees_per_s") is not None \
            or rec.get("rank_M_row_trees_per_s") is not None:
        w("## Parity set beyond binary (same-host reference CLI, "
          "identical synthetic data)")
        w("")
        w("| family | ours M r-t/s | ref C++ | speed | quality (ours / "
          "ref) |")
        w("|---|---|---|---|---|")
        if rec.get("multiclass_M_row_trees_per_s") is not None:
            w(f"| multiclass softmax | "
              f"{get(rec, 'multiclass_M_row_trees_per_s', 3)}"
              + (f" ({get(rec, 'multiclass_window_iters', 0)}-iter window)"
                 if rec.get("multiclass_window_iters") else "")
              + f" | {get(rec, 'multiclass_ref_cpp_M_row_trees_per_s', 3)}"
              f" | {get(rec, 'multiclass_vs_ref_same_host', 4)}x | "
              f"mlogloss {get(rec, 'multiclass_logloss', 5)} / "
              f"{get(rec, 'multiclass_ref_cpp_logloss', 6)} |")
        if rec.get("rank_M_row_trees_per_s") is not None:
            w(f"| lambdarank | {get(rec, 'rank_M_row_trees_per_s', 3)}"
              + (f" ({get(rec, 'rank_window_iters', 0)}-iter window)"
                 if rec.get("rank_window_iters") else "")
              + f" | {get(rec, 'rank_ref_cpp_M_row_trees_per_s', 3)} | "
              f"{get(rec, 'rank_vs_ref_same_host', 4)}x | ndcg@10 "
              f"{get(rec, 'rank_ndcg10', 5)} / "
              f"{get(rec, 'rank_ref_cpp_ndcg10', 6)} |")
        w("")
        w("(Throughput from ONE long scanned window per family — the "
          "binary block's 500-iter methodology — after the old best-of-3 "
          "short windows recorded 2x tunnel-drift swings.)")
        w("")
        w("Multiclass parity config (tools/mc_gap_ab.py A/B, CPU smoke "
          "on record): the mlogloss gap vs the reference is driven by "
          "the WAVE SCHEDULE, not precision — `gpu_use_dp` (f32 "
          "histograms) is bit-identical to base while "
          "`leafwise_wave_size=1` diverges from base at tree 0.  "
          "`leafwise_wave_size=1` is the documented parity setting (the "
          "reference's exact sequential best-first order; "
          "tests/test_wave_grower.py pins it reproducing the sequential "
          "grower's trees on the multiclass smoke shape — see "
          "BASELINE.md).")
        w("")

    fused_section(w, rec)

    prediction_section(w, rec)

    serving_section(w, rec)

    streaming_section(w, rec)

    robustness_section(w, rec)

    observability_section(w, rec)

    device_truth_section(w, rec)

    forensics_slo_section(w, rec)

    model_quality_section(w, rec)

    fleet_section(w, rec)

    tenants_section(w, rec)

    mc_name, mc = load_multichip()
    comm_section(w, mc_name, mc)

    pod_comm_section(w, mc_name, mc)

    trend_section(w)

    w("## Provenance")
    w("")
    w(f"Source record: `{name}`"
      + (f"; cross-record notes vs `{prev_name}`." if prev_name else "."))
    w("Reference-side constants (same-host C++ CLI timings, quality "
      "numbers) are recorded in bench.py next to their measurement "
      "dates; tools/measure_ref_parity.py re-measures them on an idle "
      "host.")
    w("")
    return "\n".join(L)


def main(argv):
    if len(argv) > 1:
        path = argv[1]
    else:
        recs = sorted(glob.glob(os.path.join(ROOT, "BENCH_r*.json")))
        if not recs:
            sys.exit("no BENCH_r*.json records found")
        path = recs[-1]
    out_path = argv[2] if len(argv) > 2 else os.path.join(ROOT, "PERF.md")
    rec = load(path)
    name = os.path.basename(path)
    # previous record for cross-capture drift notes
    recs = sorted(glob.glob(os.path.join(ROOT, "BENCH_r*.json")))
    prev = prev_name = None
    try:
        i = [os.path.basename(r) for r in recs].index(name)
        if i > 0:
            prev = load(recs[i - 1])
            prev_name = os.path.basename(recs[i - 1])
    except ValueError:
        pass
    text = generate(rec, name, prev, prev_name)
    with open(out_path, "w") as fh:
        fh.write(text)
    print(f"wrote {out_path} from {name}"
          + (f" (drift notes vs {prev_name})" if prev_name else ""))


if __name__ == "__main__":
    main(sys.argv)
