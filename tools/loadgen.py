"""Open-loop Poisson load generator for the serve/ subsystem.

Open-loop means arrivals are scheduled from the Poisson process ALONE —
a slow server cannot slow the offered load down (the closed-loop
fallacy: measuring a server with clients that politely wait understates
tail latency exactly when it matters).  Arrival times are drawn once
from exponential inter-arrivals at ``rate_qps``; a pool of client
threads sleeps until each scheduled instant and then blocks in
``Server.submit()`` like a real caller, so queueing delay lands in the
measured latency, not in the arrival schedule.

Core entry point (used by ``bench.py``'s serve block and the
``__graft_entry__`` smoke):

    run_loadgen(server, X, rate_qps=..., duration_s=..., ...) -> dict

with client-side outcome counts (ok/shed/timeout), client-measured
latency quantiles, achieved vs offered QPS, and the server's own
metrics snapshot.  Optional mid-run hooks drive a hot-swap under live
traffic (``swap_at_frac`` + ``swap_fn``).

CLI: ``python tools/loadgen.py input_model=<model.txt> [rate=500]
[duration=5] [rows=1] [tenants=acme:3,globex] [features from the
model]`` — builds an in-process server on the model (standing up the
named tenant lineages when ``tenants=`` is given) and prints one JSON
line of ``serve_*`` fields.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_loadgen(server, X: np.ndarray, *, rate_qps: float,
                duration_s: float, rows_per_req: int = 1,
                n_threads: int = 8, seed: int = 0,
                swap_at_frac: Optional[float] = None,
                swap_fn: Optional[Callable[[], None]] = None,
                tail_requests_after_swap: int = 0,
                check_fn: Optional[Callable] = None,
                export_artifacts_to: str = "",
                tenants=None) -> Dict[str, object]:
    """Drive ``server.submit`` with open-loop Poisson arrivals.

    ``X`` is the row pool (requests sample ``rows_per_req`` consecutive
    rows from it).  ``swap_fn`` (e.g. a ``server.publish`` closure) runs
    once from a side thread when ``swap_at_frac`` of the schedule has
    elapsed — the hot-swap-under-live-traffic probe; because a publish
    warms compile caches off the serving path, it can outlast a short
    window, so ``tail_requests_after_swap`` sends that many extra
    sequential requests once the swap has completed (deterministic
    post-swap coverage for the per-version parity check).
    ``check_fn(start, n_rows, result)`` may verify each response (parity
    bookkeeping); check failures are counted, never raised mid-run.

    Client-side telemetry lives in an obs registry (ISSUE 9): outcome
    counts are ``loadgen_requests_total{outcome=...}`` counters, the
    latency histogram is ``loadgen_latency_ms`` (exact quantiles over a
    full-run sample window), per-version counts are
    ``loadgen_version_total{version=...}`` — the returned dict is
    computed FROM the registry and carries its flat JSON dump under
    ``"client_metrics"``.  ``export_artifacts_to`` (or the
    ``LGBMV1_OBS_DIR`` env var) additionally writes the registry as a
    loadgen-role per-process artifact for ``tools/obs_aggregate.py`` to
    merge next to the server's (ISSUE 10).

    ``tenants`` (ISSUE 20) arms a weighted multi-tenant mix: a manifest
    string (``"acme:3,globex"``, serve/tenants.py grammar) or
    ``[(name, weight), ...]``.  Each arrival is tagged with a tenant
    drawn weight-proportionally from a SEPARATE seed-derived stream —
    the arrival schedule and row starts are drawn first from the
    primary stream, so a single-tenant run's schedule is bit-identical
    with the mix on or off.  Client telemetry gains the tenant
    dimension (``loadgen_requests_total{tenant,outcome}``) and the
    result carries a ``per_tenant`` outcome block."""
    from lightgbmv1_tpu.obs.metrics import Registry
    from lightgbmv1_tpu.serve.server import (RequestTimeout,
                                             ServerOverloaded)

    rng = np.random.RandomState(seed)
    n_arrivals = max(int(rate_qps * duration_s), 1)
    gaps = rng.exponential(1.0 / max(rate_qps, 1e-9), size=n_arrivals)
    arrivals = np.cumsum(gaps)
    starts = rng.randint(0, max(X.shape[0] - rows_per_req, 1),
                         size=n_arrivals)
    # tenant mix AFTER (and from a separate stream than) the arrival
    # schedule: the offered-load timeline never depends on the mix
    tenant_names: List[str] = []
    tenant_assign = None
    if tenants:
        if isinstance(tenants, str):
            from lightgbmv1_tpu.serve.tenants import parse_manifest

            pairs = [(s.name, s.weight) for s in parse_manifest(tenants)]
        else:
            pairs = [(str(n), float(w)) for n, w in tenants]
        if not pairs:
            raise ValueError(f"tenants={tenants!r} named no tenants")
        tenant_names = [n for n, _ in pairs]
        w = np.asarray([p[1] for p in pairs], np.float64)
        tenant_probs = w / w.sum()
        trng = np.random.RandomState((seed ^ 0x7e5a17) & 0x7fffffff)
        tenant_assign = trng.choice(len(pairs), size=n_arrivals,
                                    p=tenant_probs)

    reg = Registry()
    _OUTCOMES = ("ok", "shed", "timeout", "error", "check_failure",
                 "degraded")
    if tenant_assign is None:
        outcomes = reg.counter("loadgen_requests_total",
                               "Client-side request outcomes",
                               label_names=("outcome",))
        for oc in _OUTCOMES:
            outcomes.labels(outcome=oc)   # pre-touch: zeros render in
            #                               snapshots
    else:
        outcomes = reg.counter("loadgen_requests_total",
                               "Client-side request outcomes",
                               label_names=("tenant", "outcome"))
        for tn in tenant_names:
            for oc in _OUTCOMES:
                outcomes.labels(tenant=tn, outcome=oc)

    def count(oc: str, tenant: str = "") -> None:
        if tenant_assign is None:
            outcomes.labels(outcome=oc).inc()
        else:
            outcomes.labels(tenant=tenant, outcome=oc).inc()
    lat_hist = reg.histogram(
        "loadgen_latency_ms", "Client-measured request latency (ms)",
        sample_window=n_arrivals + max(int(tail_requests_after_swap), 0)
        + 16)
    version_counts = reg.counter("loadgen_version_total",
                                 "Responses per served model version",
                                 label_names=("version",))

    next_idx = [0]
    idx_lock = threading.Lock()
    t0 = time.monotonic()

    def do_one(s: int, tenant: str = ""):
        rows = X[s: s + rows_per_req]
        t_req = time.monotonic()
        try:
            if tenant_assign is None:
                res = server.submit(rows)
            else:
                res = server.submit(rows, tenant=tenant)
        except ServerOverloaded:
            count("shed", tenant)
            return
        except RequestTimeout:
            count("timeout", tenant)
            return
        except Exception:  # noqa: BLE001 — counted, run continues
            count("error", tenant)
            return
        lat = (time.monotonic() - t_req) * 1e3
        ok = True
        if check_fn is not None:
            try:
                ok = bool(check_fn(s, rows_per_req, res))
            except Exception:  # noqa: BLE001
                ok = False
        count("ok", tenant)
        if res.degraded:
            count("degraded", tenant)
        if not ok:
            count("check_failure", tenant)
        lat_hist.observe(lat)
        version_counts.labels(version=res.version).inc()

    def client():
        while True:
            with idx_lock:
                i = next_idx[0]
                if i >= n_arrivals:
                    return
                next_idx[0] += 1
            delay = t0 + arrivals[i] - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            do_one(int(starts[i]),
                   tenant_names[tenant_assign[i]]
                   if tenant_assign is not None else "")

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(max(int(n_threads), 1))]
    swapper = None
    if swap_fn is not None and swap_at_frac is not None:
        swap_t = t0 + float(arrivals[-1]) * float(swap_at_frac)

        def do_swap():
            dt = swap_t - time.monotonic()
            if dt > 0:
                time.sleep(dt)
            swap_fn()

        swapper = threading.Thread(target=do_swap, daemon=True)
        swapper.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if swapper is not None:
        swapper.join()
        n_tail = max(int(tail_requests_after_swap), 0)
        tail_starts = rng.randint(0, max(X.shape[0] - rows_per_req, 1),
                                  size=n_tail)
        tail_tenants = (trng.choice(len(tenant_names), size=n_tail,
                                    p=tenant_probs)
                        if tenant_assign is not None else None)
        for j, s in enumerate(tail_starts):
            do_one(int(s),
                   tenant_names[tail_tenants[j]]
                   if tail_tenants is not None else "")
    wall = time.monotonic() - t0

    export_dir = export_artifacts_to or os.environ.get("LGBMV1_OBS_DIR",
                                                       "")
    if export_dir:
        # the loadgen's own per-process artifact (obs/agg.py): its
        # client registry under a loadgen-role label, so
        # tools/obs_aggregate.py merges the client view next to the
        # server's in one snapshot / one Perfetto timeline
        from lightgbmv1_tpu.obs import agg as obs_agg
        from lightgbmv1_tpu.obs import events as obs_events

        ident = obs_events.identity()
        obs_agg.export_process_artifacts(
            export_dir,
            label=f"loadgen-{ident['host']}-{ident['pid']}",
            registry=reg)

    if tenant_assign is None:
        def _count_of(oc: str) -> int:
            return int(outcomes.labels(outcome=oc).get())
    else:
        def _count_of(oc: str) -> int:
            return sum(int(c.get()) for key, c in outcomes.children()
                       if key[1] == oc)
    stats = {oc: _count_of(oc) for oc in ("ok", "shed", "timeout",
                                          "error")}
    stats["check_failures"] = _count_of("check_failure")
    stats["degraded"] = _count_of("degraded")
    versions = {key[0]: int(child.get())
                for key, child in version_counts.children()}
    total = sum(stats[k] for k in ("ok", "shed", "timeout", "error"))
    snap = server.metrics_snapshot()

    def q(p):
        v = lat_hist.quantile(p)
        return None if v is None else round(v, 3)

    per_tenant = None
    if tenant_assign is not None:
        per_tenant = {
            tn: {oc: int(outcomes.labels(tenant=tn, outcome=oc).get())
                 for oc in ("ok", "shed", "timeout", "error")}
            for tn in tenant_names}
    out = {
        "offered_qps": round(rate_qps, 1),
        "achieved_qps": round(stats["ok"] / wall, 1) if wall > 0 else None,
        "duration_s": round(wall, 2),
        "requests": total,
        **stats,
        "shed_frac": round(stats["shed"] / total, 4) if total else 0.0,
        "client_p50_ms": q(0.50),
        "client_p99_ms": q(0.99),
        "client_p999_ms": q(0.999),
        "versions_served": versions,
        "server_metrics": snap,
        # the registry's own JSON view (labeled keys like
        # loadgen_requests_total{outcome="ok"}) — same store, flat dump
        "client_metrics": reg.snapshot(),
    }
    if per_tenant is not None:
        out["per_tenant"] = per_tenant
    return out


def serve_record_fields(lg: Dict[str, object]) -> Dict[str, object]:
    """Map a ``run_loadgen`` result onto the flat ``serve_*`` BENCH
    fields (bench.py's serve block and tools/perf_report.py render
    these)."""
    snap = lg.get("server_metrics", {}) or {}
    return {
        "serve_qps": lg.get("achieved_qps"),
        "serve_offered_qps": lg.get("offered_qps"),
        "serve_requests": lg.get("requests"),
        "serve_p50_ms": lg.get("client_p50_ms"),
        "serve_p99_ms": lg.get("client_p99_ms"),
        "serve_p999_ms": lg.get("client_p999_ms"),
        "serve_batch_occupancy": snap.get("batch_occupancy"),
        "serve_mean_batch_rows": snap.get("mean_batch_rows"),
        "serve_queue_depth_max": snap.get("queue_depth_max"),
        "serve_shed_frac": lg.get("shed_frac"),
        "serve_timeouts": lg.get("timeout"),
        "serve_degraded": lg.get("degraded"),
        "serve_swap_count": snap.get("swaps"),
        "serve_versions": lg.get("versions_served"),
    }


def main(argv: List[str]) -> int:
    from lightgbmv1_tpu.basic import Booster
    from lightgbmv1_tpu.config import Config
    from lightgbmv1_tpu.serve.server import build_server

    kv = Config.kv2map(argv)
    model_path = kv.pop("input_model", "")
    if not model_path:
        print(__doc__)
        return 1
    rate = float(kv.pop("rate", 500.0))
    duration = float(kv.pop("duration", 5.0))
    rows_per_req = int(kv.pop("rows", 1))
    seed = int(kv.pop("seed", 0))
    tenants = kv.pop("tenants", "")
    config = Config.from_dict(kv)
    booster = Booster(params={"verbosity": config.verbosity},
                      model_file=model_path)
    server = build_server(booster, config)
    if tenants:
        # stand the named lineages up on the in-process server, each
        # seeded with the model under test (serve/tenants.py)
        from lightgbmv1_tpu.serve.tenants import TenantRegistry

        tenreg = TenantRegistry(server)
        for spec in tenreg.add_manifest(tenants):
            tenreg.publish(spec.name, booster)
    rng = np.random.RandomState(seed + 1)
    X = rng.randn(8192, booster.num_feature())
    try:
        lg = run_loadgen(server, X, rate_qps=rate, duration_s=duration,
                         rows_per_req=rows_per_req, seed=seed,
                         tenants=tenants or None)
    finally:
        server.close()
    print(json.dumps({**serve_record_fields(lg), "loadgen": lg}))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
