"""Micro-benchmark of histogram implementations on the current backend.

Not part of the test suite; a profiling tool for the perf work.
Usage: python microbench_hist.py [N] [F] [B]
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, reps=5):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main():
    N = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    F = int(sys.argv[2]) if len(sys.argv) > 2 else 28
    B = int(sys.argv[3]) if len(sys.argv) > 3 else 64

    from lightgbmv1_tpu.ops.histogram import (
        hist_leaves_onehot, hist_leaves_scatter,
    )
    from lightgbmv1_tpu.ops.hist_pallas import hist_leaves_pallas

    rng = np.random.RandomState(0)
    binned = jnp.asarray(rng.randint(0, B, size=(F, N), dtype=np.uint8))
    g3 = jnp.asarray(rng.randn(N, 3).astype(np.float32))
    print(f"backend={jax.default_backend()} N={N} F={F} B={B}", flush=True)

    for L in (1, 2, 16, 64, 128, 256):
        leaf = jnp.asarray(rng.randint(0, L, size=N).astype(np.int32))
        row = {"L": L}
        for name, fn in [
            ("onehot", lambda: hist_leaves_onehot(binned, g3, leaf, L, B)),
            ("pallas", lambda: hist_leaves_pallas(binned, g3, leaf, L, B)),
        ]:
            try:
                dt = timeit(fn)
                # useful throughput + achieved MXU FLOPs
                flops = 2 * (L + 1) * 3 * N * F * B * 2  # bf16x2 = 2 passes
                row[name] = f"{dt*1e3:8.2f}ms {N/dt/1e6:8.1f}Mrow/s {flops/dt/1e12:6.1f}TF/s"
            except Exception as e:  # noqa
                row[name] = f"FAIL {type(e).__name__}: {e}"[:120]
        print(row, flush=True)

    # scatter once for reference at L=256 (slow on TPU presumably)
    L = 256
    leaf = jnp.asarray(rng.randint(0, L, size=N).astype(np.int32))
    try:
        dt = timeit(lambda: hist_leaves_scatter(binned, g3, leaf, L, B), reps=2)
        print({"L": L, "scatter": f"{dt*1e3:8.2f}ms {N/dt/1e6:8.1f}Mrow/s"}, flush=True)
    except Exception as e:
        print("scatter FAIL", e)


if __name__ == "__main__":
    main()
