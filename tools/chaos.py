"""Scripted chaos suite — every injected fault must be survivable.

Runs the fault scenarios the robustness substrate (PR 6) exists for,
end to end on CPU, and emits a CHAOS record with a ``chaos_ok`` guard
(wired into ``bench.py`` and ``__graft_entry__.chaos_smoke`` so a
regression in ANY recovery path trips a driver capture, not a pager):

==========================  ===============================================
scenario                    contract proven
==========================  ===============================================
``train_kill_resume``       a REAL ``os._exit`` mid-training (subprocess
                            CLI, full suite) / an in-process crash (fast
                            suite): auto-resume from the checkpoint bundle
                            reproduces the uninterrupted run's model text
                            **byte-identically**
``torn_snapshot``           the newest checkpoint is torn at write time:
                            validate-on-load rejects it, resume falls back
                            to the previous INTACT bundle, final model
                            still byte-identical
``poisoned_gradients``      a NaN-poisoned gradient pass is DETECTED at
                            the iteration boundary (``finite_guard=raise``)
                            and SURVIVED under ``finite_guard=clamp``
                            (finite model, training continues)
``publish_of_garbage``      a corrupt candidate (NaN leaves) and a publish
                            that dies mid-warm both leave the active
                            version serving bit-exact answers — the corrupt
                            model never serves a single response
``dispatcher_stall``        a wedged device batch fails its requests fast
                            (watchdog -> 503) instead of hanging the queue;
                            a DEAD dispatcher thread is restarted; traffic
                            resumes on the same version
``overload``                a burst far above capacity sheds EXPLICITLY
                            with the backlog bounded at the admission
                            depth; post-burst requests succeed
``h2d_transient``           a transient host->device transfer error is
                            retried with backoff — zero client-visible
                            failures
==========================  ===============================================

Forensics contract (ISSUE 10, obs/dump.py): every scenario also asserts
the flight recorder's behavior for its induced failure.  Scenarios that
KILL or WEDGE a process (kill-resume, torn-snapshot, poisoned-raise,
dispatcher stall) must leave EXACTLY ONE validated forensic bundle
(schema-checked, digest-intact, Perfetto-loadable trace) in the armed
crash dir; scenarios whose fault is absorbed by a recovery path
(publish-of-garbage, overload, transient H2D) must leave ZERO bundles —
a recorder that dumps on survivable faults buries the real crashes —
while still publishing the structured events that name the fault
(``serve.publish_reject``, ``serve.shed``, ``fault.injected``).  The
per-scenario ``forensics_ok`` rolls into ``chaos_ok`` and the CHAOS
record's ``forensics_ok`` field.

Usage::

    python tools/chaos.py          # full suite (includes subprocess kill)
    python tools/chaos.py --fast   # in-process deterministic subset

Prints ``CHAOS {json}``; exit code 0 iff ``chaos_ok``.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _write_data(path: str, n: int = 400, seed: int = 0) -> str:
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 5)
    y = (X[:, 0] - X[:, 1] + rng.randn(n) * 0.3 > 0).astype(float)
    np.savetxt(path, np.column_stack([y, X]), fmt="%.7g", delimiter="\t")
    return path


def _cli_args(data: str, model: str, n_trees: int = 8):
    return [f"data={data}", "objective=binary", f"num_trees={n_trees}",
            "num_leaves=7", "min_data_in_leaf=20", "snapshot_freq=2",
            f"output_model={model}", "verbosity=-1"]


def _train_problem(n=1000, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 8)
    logit = 1.5 * X[:, 0] - X[:, 1] + 0.8 * X[:, 2] * X[:, 3]
    y = (logit + rng.randn(n) * 0.4 > 0).astype(np.float64)
    return X, y


_BOOSTER_CACHE = []


def _tiny_boosters():
    """Two small models + their training rows; memoized — the serving
    scenarios only READ them (publishes copy via model text)."""
    if not _BOOSTER_CACHE:
        import lightgbmv1_tpu as lgb

        X, y = _train_problem(1200, seed=1)
        P = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
             "verbosity": -1}
        b1 = lgb.train(P, lgb.Dataset(X, label=y), num_boost_round=4,
                       verbose_eval=False)
        b2 = lgb.train(P, lgb.Dataset(X, label=y), num_boost_round=8,
                       verbose_eval=False)
        _BOOSTER_CACHE.append((b1, b2, X))
    return _BOOSTER_CACHE[0]


def _serve_cfg(**over):
    from lightgbmv1_tpu.serve import ServeConfig

    kw = dict(max_batch_rows=128, max_batch_delay_ms=1.0,
              queue_depth_rows=4096, f64_scores=True,
              retry_max=2, retry_backoff_ms=2.0, breaker_failures=3,
              watchdog_ms=250.0, predictor_kwargs={"bucket_min": 64})
    kw.update(over)
    return ServeConfig(**kw)


def _host_raw(booster, X):
    return np.asarray(booster.predict(X, raw_score=True,
                                      predict_method="host"), np.float64)


def _check_bundles(crash_dir: str, expect: int,
                   reasons: tuple = ()) -> dict:
    """Forensics assertion: exactly ``expect`` bundles in ``crash_dir``,
    each fully validated (schema + digests + Perfetto-loadable trace),
    the first one's reason in ``reasons`` when given."""
    from lightgbmv1_tpu.obs import dump

    bundles = dump.list_bundles(crash_dir) if crash_dir else []
    out = {"bundles": len(bundles), "expect": expect}
    if len(bundles) != expect:
        out["forensics_ok"] = False
        return out
    try:
        for b in bundles:
            manifest = dump.validate_bundle(b)
            out["bundle_reason"] = manifest["reason"]
            out["bundle_error_text"] = str(
                manifest.get("error", ""))[:300]
        ok = (not reasons or out.get("bundle_reason") in reasons)
    except Exception as e:  # noqa: BLE001 — an invalid bundle FAILS
        out["bundle_error"] = f"{type(e).__name__}: {e}"[:200]
        ok = False
    out["forensics_ok"] = bool(ok)
    return out


def _count_events(since_seq: int, kind: str) -> int:
    from lightgbmv1_tpu.obs import events

    return len(events.tail(since_seq=since_seq, kind_prefix=kind))


# ---------------------------------------------------------------------------
# scenarios — each returns a dict with at least {"ok": bool}
# ---------------------------------------------------------------------------


def scenario_train_kill_resume(tmp: str, subprocess_kill: bool) -> dict:
    """Kill training after the 2nd snapshot; rerunning the same command
    must auto-resume from the checkpoint bundle and produce model text
    BYTE-IDENTICAL to a run that never died.  ``subprocess_kill=True``
    uses a real child process and ``os._exit(137)`` (no cleanup, no
    flush); the fast variant crashes in-process via an injected raise.
    Either way the dying run must leave exactly one validated forensic
    bundle (the injected kill dumps at the faults seam; the in-process
    raise dumps on run_train's way out)."""
    from lightgbmv1_tpu.cli import main as cli_main
    from lightgbmv1_tpu.obs import dump
    from lightgbmv1_tpu.utils import faults
    from lightgbmv1_tpu.utils.faults import FaultInjected, FaultSpec

    data = _write_data(os.path.join(tmp, "train.tsv"))
    model = os.path.join(tmp, "m.txt")
    crash_dir = os.path.join(tmp, "crash")
    args = _cli_args(data, model)

    cli_main(args)                       # straight run
    with open(model) as fh:
        straight = fh.read()
    for p in list(os.listdir(tmp)):      # clean slate for the crash run
        if p.startswith("m.txt"):
            os.remove(os.path.join(tmp, p))

    crash_args = args + [f"crash_dir={crash_dir}"]
    plan = [{"kind": "snapshot", "mode": "kill", "at": 2}]
    if subprocess_kill:
        env = dict(os.environ, LGBMV1_FAULTS=json.dumps(plan),
                   JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        proc = subprocess.run(
            [sys.executable, "-m", "lightgbmv1_tpu"] + crash_args,
            env=env, cwd=tmp, capture_output=True, text=True)
        crashed = proc.returncode == 137
    else:
        with faults.inject(FaultSpec("snapshot", mode="raise", at=2)):
            try:
                cli_main(crash_args)
                crashed = False
            except FaultInjected:
                crashed = True
        dump.disarm()                    # the CLI armed it; scope it here
    model_absent = not os.path.exists(model)
    forensics = _check_bundles(crash_dir, expect=1,
                               reasons=("fault_kill", "train_crash"))

    cli_main(args)                       # auto-resume
    with open(model) as fh:
        resumed = fh.read()
    ok = (crashed and model_absent and resumed == straight
          and forensics["forensics_ok"])
    return {"ok": ok, "crashed": crashed, "model_absent": model_absent,
            "bit_identical": resumed == straight,
            "kill": "subprocess" if subprocess_kill else "in-process",
            **forensics}


def scenario_torn_snapshot(tmp: str) -> dict:
    """The NEWEST checkpoint bundle is torn at write time (injected
    non-atomic half-write) and the run dies there: validate-on-load must
    reject the torn bundle, fall back to the previous intact one, and
    the completed resume must still be byte-identical to the
    uninterrupted run."""
    from lightgbmv1_tpu.cli import main as cli_main
    from lightgbmv1_tpu.utils import faults
    from lightgbmv1_tpu.utils.faults import FaultInjected, FaultSpec

    data = _write_data(os.path.join(tmp, "train.tsv"))
    model = os.path.join(tmp, "m.txt")
    args = _cli_args(data, model, n_trees=8)

    cli_main(args)
    with open(model) as fh:
        straight = fh.read()
    for p in list(os.listdir(tmp)):
        if p.startswith("m.txt"):
            os.remove(os.path.join(tmp, p))

    # tear the 2nd checkpoint write (iteration 4), then crash right after
    from lightgbmv1_tpu.obs import dump

    crash_dir = os.path.join(tmp, "crash")
    with faults.inject(
            FaultSpec("file_write", mode="truncate", match=".ckpt_iter_4"),
            FaultSpec("snapshot", mode="raise", at=2)):
        try:
            cli_main(args + [f"crash_dir={crash_dir}"])
            crashed = False
        except FaultInjected:
            crashed = True
    dump.disarm()
    forensics = _check_bundles(crash_dir, expect=1,
                               reasons=("train_crash",))
    torn = os.path.join(tmp, "m.txt.ckpt_iter_4")
    from lightgbmv1_tpu.io.checkpoint import (CheckpointError,
                                              validate_checkpoint)

    torn_rejected = False
    try:
        validate_checkpoint(torn)
    except CheckpointError:
        torn_rejected = True

    cli_main(args)                       # resume: must fall back to iter 2
    with open(model) as fh:
        resumed = fh.read()
    ok = (crashed and torn_rejected and resumed == straight
          and forensics["forensics_ok"])
    return {"ok": ok, "crashed": crashed, "torn_rejected": torn_rejected,
            "bit_identical": resumed == straight, **forensics}


def scenario_poisoned_gradients() -> dict:
    """NaN-poisoned gradient pass: ``finite_guard=raise`` detects it at
    the iteration boundary (and the armed flight recorder dumps exactly
    one bundle naming the poisoned iteration); ``finite_guard=clamp``
    survives it with a finite model; guard off documents the
    silent-absorption baseline."""
    import lightgbmv1_tpu as lgb
    from lightgbmv1_tpu.models.gbdt import FiniteGuardError
    from lightgbmv1_tpu.obs import dump, events
    from lightgbmv1_tpu.utils import faults
    from lightgbmv1_tpu.utils.faults import FaultSpec

    X, y = _train_problem()
    P = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 20,
         "verbosity": -1}

    detected = False
    mark = events.seq()
    crash_dir = tempfile.mkdtemp(prefix="lgbm_chaos_fg_")
    try:
        with dump.armed_dir(crash_dir):
            with faults.inject(FaultSpec("grad_poison", payload=2)):
                try:
                    lgb.train({**P, "finite_guard": "raise"},
                              lgb.Dataset(X, label=y), num_boost_round=6,
                              verbose_eval=False)
                except FiniteGuardError:
                    detected = True
        forensics = _check_bundles(crash_dir, expect=1,
                                   reasons=("finite_guard",))
        forensics["guard_events"] = _count_events(mark,
                                                  "guard.finite_guard")
        forensics["forensics_ok"] = bool(
            forensics["forensics_ok"] and forensics["guard_events"] >= 1)
    finally:
        shutil.rmtree(crash_dir, ignore_errors=True)

    with faults.inject(FaultSpec("grad_poison", payload=2)):
        b = lgb.train({**P, "finite_guard": "clamp"},
                      lgb.Dataset(X, label=y), num_boost_round=6,
                      verbose_eval=False)
    clamped_finite = bool(np.isfinite(b.predict(X)).all()) \
        and b.num_trees() == 6
    # clamp must also leave the model text loadable + structurally valid
    import lightgbmv1_tpu as lgb2

    reloaded = lgb2.Booster(model_str=b.model_to_string())
    reload_ok = reloaded.num_trees() == 6
    ok = (detected and clamped_finite and reload_ok
          and forensics["forensics_ok"])
    return {"ok": ok, "detected_at_boundary": detected,
            "clamp_survived": clamped_finite, "reload_ok": reload_ok,
            **forensics}


def scenario_publish_of_garbage() -> dict:
    """A corrupt model (NaN leaves) and a publish dying mid-warm: the
    active version must keep serving bit-exact answers throughout — the
    corrupt candidate never serves a single response.  Forensics: both
    rejections are first-class ``serve.publish_reject`` events and the
    recovered fault writes NO crash bundle."""
    import lightgbmv1_tpu as lgb
    from lightgbmv1_tpu.obs import dump, events
    from lightgbmv1_tpu.serve import PublishValidationError, Server
    from lightgbmv1_tpu.utils import faults
    from lightgbmv1_tpu.utils.faults import FaultInjected, FaultSpec

    b1, b2, X = _tiny_boosters()
    mark = events.seq()
    crash_dir = tempfile.mkdtemp(prefix="lgbm_chaos_pub_")
    dump.arm(crash_dir)
    srv = Server(b1, config=_serve_cfg())
    try:
        want = _host_raw(b1, X[:16])
        corrupt = lgb.Booster(model_str=b2.model_to_string())
        corrupt._loaded.trees[1].leaf_value[:] = np.nan
        rejected = False
        try:
            srv.publish(corrupt)
        except PublishValidationError:
            rejected = True
        midwarm_failed = False
        with faults.inject(FaultSpec("publish_warm", mode="raise", at=2)):
            try:
                srv.publish(b2)
            except FaultInjected:
                midwarm_failed = True
        still_v1 = srv.version() == "v1"
        r = srv.submit(X[:16])
        served_exact = (r.version == "v1"
                        and np.array_equal(r.values[:, 0], want))
        clean_tag = srv.publish(b2)       # recovery: a clean publish works
        r2 = srv.submit(X[:16])
        recovered = (r2.version == clean_tag and np.array_equal(
            r2.values[:, 0], _host_raw(b2, X[:16])))
        rejects = srv.metrics_snapshot()["publish_rejects"]
        forensics = _check_bundles(crash_dir, expect=0)
        forensics["reject_events"] = _count_events(
            mark, "serve.publish_reject")
        forensics["forensics_ok"] = bool(
            forensics["forensics_ok"] and forensics["reject_events"] >= 2)
        ok = (rejected and midwarm_failed and still_v1 and served_exact
              and recovered and rejects == 2
              and forensics["forensics_ok"])
        return {"ok": ok, "garbage_rejected": rejected,
                "midwarm_failed": midwarm_failed,
                "active_served_exact": served_exact,
                "clean_publish_recovered": recovered,
                "publish_rejects": rejects, **forensics}
    finally:
        srv.close()
        dump.disarm()
        shutil.rmtree(crash_dir, ignore_errors=True)


def scenario_dispatcher_stall() -> dict:
    """A wedged device batch: the watchdog fails its requests fast (the
    503 path) instead of hanging the queue, and a DEAD dispatcher thread
    is restarted — traffic resumes on the same version both times.
    Forensics: the wedge is a crash-grade moment — EXACTLY ONE validated
    bundle (reason watchdog_stall; the later dispatcher death hits the
    once-per-arming latch, it must not shred the stall evidence)."""
    from lightgbmv1_tpu.obs import dump, events
    from lightgbmv1_tpu.serve import DispatcherDied, DispatcherStalled, \
        Server
    from lightgbmv1_tpu.utils import faults
    from lightgbmv1_tpu.utils.faults import FaultSpec

    b1, _, X = _tiny_boosters()
    mark = events.seq()
    crash_dir = tempfile.mkdtemp(prefix="lgbm_chaos_wd_")
    dump.arm(crash_dir)
    srv = Server(b1, config=_serve_cfg(watchdog_ms=200.0))
    try:
        srv.submit(X[:4])                 # warm
        stall_s = 1.0
        with faults.inject(FaultSpec("dispatch", mode="stall", at=1,
                                     stall_s=stall_s)):
            t0 = time.monotonic()
            stalled_fast = False
            try:
                srv.submit(X[:4])
            except DispatcherStalled:
                stalled_fast = (time.monotonic() - t0) < stall_s
        time.sleep(stall_s + 0.2)         # let the wedged batch drain
        r = srv.submit(X[:4])
        post_stall = r.version == "v1"

        died = False
        with faults.inject(FaultSpec("dispatch", mode="exit_thread", at=1)):
            try:
                srv.submit(X[:4])
            except (DispatcherDied, DispatcherStalled):
                died = True
        deadline = time.monotonic() + 3.0
        while not srv.dispatcher_alive() and time.monotonic() < deadline:
            time.sleep(0.05)
        r2 = srv.submit(X[:4])
        snap = srv.metrics_snapshot()
        restarted = snap["dispatcher_restarts"] >= 1 and r2.version == "v1"
        healthy = srv.health()["ok"]
        forensics = _check_bundles(crash_dir, expect=1,
                                   reasons=("watchdog_stall",))
        forensics["stall_events"] = _count_events(
            mark, "serve.watchdog_stall")
        forensics["restart_events"] = _count_events(
            mark, "serve.dispatcher_restart")
        forensics["forensics_ok"] = bool(
            forensics["forensics_ok"] and forensics["stall_events"] >= 1
            and forensics["restart_events"] >= 1)
        ok = (stalled_fast and post_stall and died and restarted
              and healthy and forensics["forensics_ok"])
        return {"ok": ok, "stalled_failed_fast": stalled_fast,
                "post_stall_recovered": post_stall,
                "dispatcher_died": died,
                "watchdog_restarted": restarted, "healthy_after": healthy,
                "watchdog_failures": snap["watchdog_failures"],
                **forensics}
    finally:
        srv.close()
        dump.disarm()
        shutil.rmtree(crash_dir, ignore_errors=True)


def scenario_overload() -> dict:
    """A burst far above capacity into a small admission queue: explicit
    sheds, backlog bounded at the configured depth, zero hangs, and
    post-burst requests succeed.  Forensics: sheds are recoverable —
    ``serve.shed`` events, NO crash bundle."""
    from lightgbmv1_tpu.obs import dump, events
    from lightgbmv1_tpu.serve import Server, ServerOverloaded

    b1, _, X = _tiny_boosters()
    depth = 64
    mark = events.seq()
    crash_dir = tempfile.mkdtemp(prefix="lgbm_chaos_ov_")
    dump.arm(crash_dir)
    srv = Server(b1, config=_serve_cfg(
        max_batch_rows=32, queue_depth_rows=depth,
        max_batch_delay_ms=20.0, watchdog_ms=0.0))
    try:
        srv.submit(X[:4])
        results = {"ok": 0, "shed": 0, "other": 0}
        lock = threading.Lock()

        def client(i):
            try:
                srv.submit(X[(i * 16) % 512: (i * 16) % 512 + 16])
                key = "ok"
            except ServerOverloaded:
                key = "shed"
            except Exception:  # noqa: BLE001 — anything else is a failure
                key = "other"
            with lock:
                results[key] += 1

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(32)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        hung = any(t.is_alive() for t in threads)
        snap = srv.metrics_snapshot()
        bounded = snap["queue_depth_max"] <= depth
        r = srv.submit(X[:4])             # post-burst service
        forensics = _check_bundles(crash_dir, expect=0)
        forensics["shed_events"] = _count_events(mark, "serve.shed")
        forensics["forensics_ok"] = bool(
            forensics["forensics_ok"]
            and forensics["shed_events"] == results["shed"])
        ok = (not hung and results["shed"] > 0 and results["other"] == 0
              and bounded and r.version == "v1"
              and results["ok"] + results["shed"] == 32
              and forensics["forensics_ok"])
        return {"ok": ok, "served": results["ok"], "shed": results["shed"],
                "failed": results["other"], "hung": hung,
                "queue_depth_max": snap["queue_depth_max"],
                "queue_bounded": bounded, **forensics}
    finally:
        srv.close()
        dump.disarm()
        shutil.rmtree(crash_dir, ignore_errors=True)


def scenario_h2d_transient() -> dict:
    """A transient host->device transfer failure inside the device batch
    is retried with backoff: the client sees a normal answer, never an
    error.  Forensics: the injection is a ``fault.injected`` event and
    the retried-and-recovered fault writes NO crash bundle."""
    from lightgbmv1_tpu.obs import dump, events
    from lightgbmv1_tpu.serve import Server
    from lightgbmv1_tpu.utils import faults
    from lightgbmv1_tpu.utils.faults import FaultSpec

    b1, _, X = _tiny_boosters()
    mark = events.seq()
    crash_dir = tempfile.mkdtemp(prefix="lgbm_chaos_h2d_")
    dump.arm(crash_dir)
    srv = Server(b1, config=_serve_cfg())
    try:
        srv.submit(X[:4])
        want = _host_raw(b1, X[:8])
        with faults.inject(FaultSpec("h2d", mode="raise", at=1)):
            r = srv.submit(X[:8])
        snap = srv.metrics_snapshot()
        exact = np.array_equal(r.values[:, 0], want)
        forensics = _check_bundles(crash_dir, expect=0)
        forensics["fault_events"] = _count_events(mark, "fault.injected")
        forensics["forensics_ok"] = bool(
            forensics["forensics_ok"] and forensics["fault_events"] >= 1)
        ok = (exact and snap["retries"] >= 1 and snap["errors"] == 0
              and forensics["forensics_ok"])
        return {"ok": ok, "answer_exact": exact,
                "retries": snap["retries"], "errors": snap["errors"],
                **forensics}
    finally:
        srv.close()
        dump.disarm()
        shutil.rmtree(crash_dir, ignore_errors=True)


# ---------------------------------------------------------------------------
# fleet scenarios (ISSUE 11) — elastic training recovery + self-healing
# replicated serving.  Forensics contract per scenario: kill/wedge-grade
# events leave EXACTLY ONE validated bundle, faults recovered at the
# fleet layer leave ZERO, and every scenario's per-process obs artifacts
# merge into ONE obs/agg.py trace.
# ---------------------------------------------------------------------------


def _fleet_cfg(**over):
    from lightgbmv1_tpu.serve import ServeConfig

    kw = dict(max_batch_rows=64, max_batch_delay_ms=1.0,
              queue_depth_rows=4096, f64_scores=True,
              retry_max=1, retry_backoff_ms=2.0, breaker_failures=0,
              watchdog_ms=150.0, predictor_kwargs={"bucket_min": 64})
    kw.update(over)
    return ServeConfig(**kw)


def scenario_trainer_worker_kill(tmp: str, two_process: bool) -> dict:
    """Elastic training recovery: a worker of a (2-process jax.distributed
    when supported) elastic run is KILLED at iteration 3 via the
    ``peer_dead`` seam; survivors detect the stale lease within the
    bounded window and exit for re-bootstrap; the coordinator respawns
    the fleet from the newest checkpoint bundle; the recovered final
    model text is BYTE-IDENTICAL to an uninterrupted run.  Forensics:
    exactly ONE bundle (the killed worker's ``fault_kill``), and every
    worker generation's obs artifacts merge into one trace."""
    import numpy as np

    from lightgbmv1_tpu.obs import agg as obs_agg
    from lightgbmv1_tpu.parallel.cluster import cpu_multiprocess_supported
    from lightgbmv1_tpu.parallel.elastic import (ElasticConfig,
                                                 ElasticCoordinator)

    world = 2 if (two_process and cpu_multiprocess_supported()) else 1
    rng = np.random.RandomState(0)
    X = rng.randn(1600, 5)
    y = (X[:, 0] - X[:, 1] > 0).astype(float)
    data = os.path.join(tmp, "train.tsv")
    np.savetxt(data, np.column_stack([y, X]), fmt="%.7g", delimiter="\t")
    cfg = ElasticConfig(world=world, devices_per_proc=2,
                        lease_timeout_s=2.0, max_restarts=1)
    base_env = {k: v for k, v in os.environ.items()
                if k not in ("LGBMV1_CRASH_DIR", "LGBMV1_OBS_DIR",
                             "LGBMV1_FAULTS")}

    def run_one(name, fault_env=None, crash=None, obsd=None):
        workdir = os.path.join(tmp, name)
        env = dict(base_env)
        if crash:
            env["LGBMV1_CRASH_DIR"] = crash
        if obsd:
            env["LGBMV1_OBS_DIR"] = obsd
        coord = ElasticCoordinator(
            workdir,
            worker_args={"data": data,
                         "model_out": os.path.join(workdir, "model.txt"),
                         "iterations": 6, "snapshot_freq": 2},
            config=cfg, fault_env=fault_env, env=env)
        res = coord.run()
        model = os.path.join(workdir, "model.txt")
        text = open(model).read() if os.path.exists(model) else None
        return res, text

    res_a, straight = run_one("straight")
    kill_rank = world - 1
    crash = os.path.join(tmp, "crash")
    obsd = os.path.join(tmp, "obs")
    plan = [{"kind": "peer_dead", "mode": "kill",
             "match": f"rank{kill_rank}:iter3"}]
    res_b, resumed = run_one(
        "killed", fault_env={"LGBMV1_FAULTS": json.dumps(plan)},
        crash=crash, obsd=obsd)
    forensics = _check_bundles(crash, expect=1, reasons=("fault_kill",))
    agg_ok = False
    try:
        summ = obs_agg.aggregate_dir(obsd)
        # every completed worker exported an artifact; the killed one's
        # evidence is its crash bundle.  world lanes minimum: each
        # surviving/respawned rank traces its iterations.
        agg_ok = (len(summ["sources"]) >= world
                  and summ["lanes"] >= world)
    except Exception as e:  # noqa: BLE001
        forensics["agg_error"] = f"{type(e).__name__}: {e}"[:200]
    forensics["forensics_ok"] = bool(forensics["forensics_ok"] and agg_ok)
    bit_identical = (straight is not None and resumed is not None
                     and straight == resumed)
    detected = (world == 1 or res_b.peer_lost_exits >= 1)
    ok = (res_a.ok and res_b.ok and res_b.restarts == 1 and detected
          and bit_identical and forensics["forensics_ok"])
    return {"ok": ok, "world": world, "restarts": res_b.restarts,
            "peer_lost_exits": res_b.peer_lost_exits,
            "recovery_s": res_b.recovery_s,
            "bit_identical": bit_identical, "agg_ok": agg_ok,
            **forensics}


def _export_fleet_artifacts(obsd: str, fleet, router) -> None:
    from lightgbmv1_tpu.obs import agg as obs_agg

    for r in fleet.replicas:
        obs_agg.export_process_artifacts(
            obsd, label=f"replica-{r.name}",
            registry=r.metrics.registry)
    obs_agg.export_process_artifacts(
        obsd, label="router", registry=router.metrics.registry)


def scenario_replica_kill() -> dict:
    """A replica killed mid-traffic under open-loop loadgen: the router
    retries its in-flight/queued failures onto healthy replicas — ZERO
    client-visible errors (bounded retry latency only), the dead
    replica is health-check ejected.  Forensics: a fleet-recovered kill
    writes NO bundle; the ejection is a first-class event; all
    per-process artifacts merge into one trace."""
    import numpy as np

    from lightgbmv1_tpu.obs import dump, events
    from lightgbmv1_tpu.serve import Fleet, Router, RouterConfig
    from tools.loadgen import run_loadgen

    b1, _, X = _tiny_boosters()
    want = {}

    def check(start, n_rows, res):
        key = (start, n_rows)
        if key not in want:
            want[key] = _host_raw(b1, X[start:start + n_rows])
        return np.array_equal(res.values[:, 0], want[key])

    mark = events.seq()
    crash_dir = tempfile.mkdtemp(prefix="lgbm_chaos_rk_")
    obsd = tempfile.mkdtemp(prefix="lgbm_chaos_rk_obs_")
    dump.arm(crash_dir)
    fleet = Fleet(b1, n_replicas=3, config=_fleet_cfg())
    router = Router(fleet, RouterConfig(health_period_ms=15.0,
                                        retry_max=2, hedge_ms=60.0))
    try:
        router.submit(X[:4])          # warm every bucket path
        lg = run_loadgen(
            router, X[:512], rate_qps=250.0, duration_s=1.6,
            rows_per_req=4, n_threads=6,
            swap_at_frac=0.4,
            swap_fn=lambda: fleet.replica("r1").close(),
            check_fn=check)
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            h = router.health()
            if "r1" in h["ejected_replicas"]:
                break
            time.sleep(0.05)
        h = router.health()
        ejected = "r1" in h["ejected_replicas"]
        zero_errors = (lg["error"] == 0 and lg["timeout"] == 0
                       and lg["shed"] == 0 and lg["check_failures"] == 0)
        snap = router.metrics_snapshot()
        from lightgbmv1_tpu.obs import agg as obs_agg

        _export_fleet_artifacts(obsd, fleet, router)
        try:
            summ = obs_agg.aggregate_dir(obsd)
            agg_ok = len(summ["sources"]) >= 4   # 3 replicas + router
        except Exception:  # noqa: BLE001
            agg_ok = False
        forensics = _check_bundles(crash_dir, expect=0)
        forensics["eject_events"] = _count_events(
            mark, "router.replica_ejected")
        forensics["forensics_ok"] = bool(
            forensics["forensics_ok"]
            and forensics["eject_events"] >= 1 and agg_ok)
        ok = (zero_errors and ejected and lg["ok"] > 0
              and snap["retries"] >= 1
              and forensics["forensics_ok"])
        return {"ok": ok, "served": lg["ok"], "errors": lg["error"],
                "timeouts": lg["timeout"], "sheds": lg["shed"],
                "check_failures": lg["check_failures"],
                "router_retries": snap["retries"],
                "ejected": ejected, "agg_ok": agg_ok, **forensics}
    finally:
        router.close()
        fleet.close()
        dump.disarm()
        shutil.rmtree(crash_dir, ignore_errors=True)
        shutil.rmtree(obsd, ignore_errors=True)


def scenario_wedged_replica() -> dict:
    """One replica's device batch wedges (``replica_wedge`` stall): its
    watchdog fails the stuck requests fast, the router retries them
    onto healthy replicas (zero client-visible errors), the health
    poller EJECTS the wedged replica (``wedged`` rides /healthz) and
    READMITS it once the stall drains.  Forensics: a wedge is
    crash-grade — exactly ONE bundle, reason watchdog_stall."""
    import numpy as np

    from lightgbmv1_tpu.obs import dump, events
    from lightgbmv1_tpu.serve import Fleet, Router, RouterConfig
    from lightgbmv1_tpu.utils import faults
    from lightgbmv1_tpu.utils.faults import FaultSpec

    b1, _, X = _tiny_boosters()
    want = _host_raw(b1, X[:4])
    mark = events.seq()
    crash_dir = tempfile.mkdtemp(prefix="lgbm_chaos_wr_")
    dump.arm(crash_dir)
    fleet = Fleet(b1, n_replicas=3, config=_fleet_cfg())
    router = Router(fleet, RouterConfig(health_period_ms=15.0,
                                        eject_after=2, readmit_after=2,
                                        retry_max=2, hedge_ms=50.0))
    try:
        router.submit(X[:4])
        stall_s = 1.0
        errors = 0
        served = 0
        with faults.inject(FaultSpec("replica_wedge", mode="stall",
                                     at=1, stall_s=stall_s, match="r0")):
            t0 = time.monotonic()
            while time.monotonic() - t0 < stall_s + 0.3:
                try:
                    r = router.submit(X[:4])
                    served += 1
                    if not np.array_equal(r.values[:, 0], want):
                        errors += 1
                except Exception:  # noqa: BLE001
                    errors += 1
                time.sleep(0.03)
        ejected_during = any(
            rs["ejections"] >= 1
            for rs in router.replica_states().values())
        deadline = time.monotonic() + 3.0
        readmitted = False
        while time.monotonic() < deadline:
            h = router.health()
            if "r0" in h["healthy_replicas"]:
                readmitted = True
                break
            time.sleep(0.05)
        forensics = _check_bundles(crash_dir, expect=1,
                                   reasons=("watchdog_stall",))
        forensics["stall_events"] = _count_events(
            mark, "serve.watchdog_stall")
        forensics["eject_events"] = _count_events(
            mark, "router.replica_ejected")
        forensics["forensics_ok"] = bool(
            forensics["forensics_ok"] and forensics["stall_events"] >= 1
            and forensics["eject_events"] >= 1)
        ok = (errors == 0 and served > 0 and ejected_during
              and readmitted and forensics["forensics_ok"])
        return {"ok": ok, "served": served, "errors": errors,
                "ejected_during_wedge": ejected_during,
                "readmitted": readmitted, **forensics}
    finally:
        router.close()
        fleet.close()
        dump.disarm()
        shutil.rmtree(crash_dir, ignore_errors=True)


def scenario_partial_publish_rollback() -> dict:
    """Two-phase fleet publish with one replica's warm phase dying
    (``publish_warm`` fault targeted at replica r2): the WHOLE fleet
    publish aborts with zero replicas swapped — every replica keeps
    serving the prior version BIT-EXACTLY, tags stay aligned, and a
    later clean publish succeeds fleet-wide.  Forensics: recovered
    fault — no bundle; the abort and per-replica reject are first-class
    events."""
    import numpy as np

    from lightgbmv1_tpu.obs import dump, events
    from lightgbmv1_tpu.serve import (Fleet, FleetPublishError, Router,
                                      RouterConfig)
    from lightgbmv1_tpu.utils import faults
    from lightgbmv1_tpu.utils.faults import FaultInjected, FaultSpec

    b1, b2, X = _tiny_boosters()
    mark = events.seq()
    crash_dir = tempfile.mkdtemp(prefix="lgbm_chaos_pp_")
    dump.arm(crash_dir)
    fleet = Fleet(b1, n_replicas=3, config=_fleet_cfg())
    router = Router(fleet, RouterConfig(health_period_ms=15.0))
    try:
        want_v1 = _host_raw(b1, X[:16])
        aborted = False
        with faults.inject(FaultSpec("publish_warm", mode="raise",
                                     match="r2:")):
            try:
                fleet.publish(b2)
            except FleetPublishError as e:
                aborted = "r2" in e.causes
        still_v1 = fleet.version() == "v1"
        per_replica_exact = all(
            np.array_equal(
                np.asarray(r.submit(X[:16]).values[:, 0]), want_v1)
            and r.submit(X[:16]).version == "v1"
            for r in fleet.replicas)
        clean_tag = fleet.publish(b2)
        aligned = fleet.version() == clean_tag
        want_v2 = _host_raw(b2, X[:16])
        recovered = np.array_equal(
            np.asarray(router.submit(X[:16]).values[:, 0]), want_v2)
        forensics = _check_bundles(crash_dir, expect=0)
        forensics["abort_events"] = _count_events(
            mark, "fleet.publish_abort")
        forensics["reject_events"] = _count_events(
            mark, "serve.publish_reject")
        forensics["forensics_ok"] = bool(
            forensics["forensics_ok"]
            and forensics["abort_events"] >= 1
            and forensics["reject_events"] >= 1)
        ok = (aborted and still_v1 and per_replica_exact and aligned
              and recovered and forensics["forensics_ok"])
        return {"ok": ok, "aborted": aborted, "still_v1": still_v1,
                "per_replica_exact": per_replica_exact,
                "clean_tag": clean_tag, "tags_aligned": aligned,
                "clean_publish_recovered": recovered, **forensics}
    finally:
        router.close()
        fleet.close()
        dump.disarm()
        shutil.rmtree(crash_dir, ignore_errors=True)


# ---------------------------------------------------------------------------
# suite
# ---------------------------------------------------------------------------


def run_suite(fast: bool = False) -> dict:
    """Run the scenarios; ``fast=True`` swaps the subprocess kill for the
    in-process crash (the tier-1/bench subset — same recovery paths, no
    child-interpreter cost).  Returns the CHAOS record."""
    scenarios = {}

    def run(name, fn, *a, **kw):
        t0 = time.time()
        try:
            out = fn(*a, **kw)
        except Exception as e:  # noqa: BLE001 — a crashed scenario FAILS
            out = {"ok": False,
                   "error": f"{type(e).__name__}: {e}"[:200]}
        out["seconds"] = round(time.time() - t0, 2)
        scenarios[name] = out

    tmp = tempfile.mkdtemp(prefix="lgbm_chaos_")
    try:
        for sub in ("kill", "torn"):
            os.makedirs(os.path.join(tmp, sub), exist_ok=True)
        run("train_kill_resume", scenario_train_kill_resume,
            os.path.join(tmp, "kill"), not fast)
        run("torn_snapshot", scenario_torn_snapshot,
            os.path.join(tmp, "torn"))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    run("poisoned_gradients", scenario_poisoned_gradients)
    run("publish_of_garbage", scenario_publish_of_garbage)
    run("dispatcher_stall", scenario_dispatcher_stall)
    run("overload", scenario_overload)
    run("h2d_transient", scenario_h2d_transient)

    # fleet scenarios (ISSUE 11): full suite runs the trainer kill on a
    # REAL 2-process jax.distributed cluster; --fast degrades to a
    # 1-process elastic run (same coordinator/bundle/resume machinery,
    # no cross-process collectives) to bound the bench wall
    tmp = tempfile.mkdtemp(prefix="lgbm_chaos_fleet_")
    try:
        run("trainer_worker_kill", scenario_trainer_worker_kill,
            tmp, not fast)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    run("replica_kill", scenario_replica_kill)
    run("wedged_replica", scenario_wedged_replica)
    run("partial_publish_rollback", scenario_partial_publish_rollback)

    fleet_names = ("trainer_worker_kill", "replica_kill",
                   "wedged_replica", "partial_publish_rollback")
    record = {
        "metric": "chaos suite (scripted fault injection, CPU)",
        "n_scenarios": len(scenarios),
        "scenarios": scenarios,
        "chaos_ok": all(s.get("ok") for s in scenarios.values()),
        # the flight-recorder contract across ALL scenarios: bundles for
        # kills/wedges, none for recovered faults, every bundle valid
        "forensics_ok": all(s.get("forensics_ok", False)
                            for s in scenarios.values()),
        # the fault-tolerant-fleet subset (ISSUE 11) as its own guard
        "chaos_fleet_ok": all(scenarios.get(k, {}).get("ok")
                              for k in fleet_names),
        "fast": bool(fast),
    }
    return record


def main(argv) -> int:
    fast = "--fast" in argv
    record = run_suite(fast=fast)
    print("CHAOS " + json.dumps(record))
    return 0 if record["chaos_ok"] else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
