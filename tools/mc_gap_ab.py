"""Multiclass parity-gap diagnostic A/B (VERDICT r5 #1, first step).

The recorded parity gap: at the multiclass bench config (250k rows x 28
features, 5 classes, 127 leaves, 50 iters) this framework holds mlogloss
0.851 vs the reference C++'s 0.830, while the small-scale (20-iter) gap is
0.005.  The round-5 record attributed it to "ulp-level split divergence
compounding over 250 trees" WITHOUT evidence — this tool puts a named
mechanism on record by A/B-ing the two levers that hypothesis implies,
each against the default run on identical data:

* ``wave1``  — ``leafwise_wave_size=1``: the exact sequential best-first
  split ORDER (the reference's schedule).  If the gap closes here, the
  wave schedule's round-commit batching is the mechanism, not ulp noise.
* ``dp_f32`` — ``gpu_use_dp=true``: f32 histograms everywhere (disables
  the depth-adaptive bf16 drop).  If the gap closes here, histogram
  precision is the mechanism.

For every variant the FIRST DIVERGENT TREE against the base run is
dumped: tree index, node index, and both sides' (feature, threshold bin,
gain) at the divergence — the concrete split where the trajectories part,
reproducible from the seeds alone (all data is generated, no files).

Run on the device session: ``python tools/mc_gap_ab.py``.  Environment
knobs: MC_AB_ROWS / MC_AB_ITERS (CPU smoke: MC_AB_ROWS=20000).
Prints one JSON line per variant.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import make_multiclass_data  # noqa: E402

import jax  # noqa: E402

from lightgbmv1_tpu.config import Config  # noqa: E402
from lightgbmv1_tpu.io.dataset import BinnedDataset  # noqa: E402
from lightgbmv1_tpu.models.gbdt import create_boosting  # noqa: E402

ON_CPU = jax.default_backend() == "cpu"
N = int(os.environ.get("MC_AB_ROWS", 20_000 if ON_CPU else 250_000))
NV = max(N // 5, 1000)
IT = int(os.environ.get("MC_AB_ITERS", 10 if ON_CPU else 50))
CLS = 5

BASE = {
    "objective": "multiclass", "num_class": CLS, "num_leaves": 127,
    "max_bin": 63, "learning_rate": 0.1, "min_data_in_leaf": 20,
    "metric": "multi_logloss", "verbosity": -1, "tree_growth": "leafwise",
}

# the levers of the recorded "ulp divergence" hypothesis, isolated
VARIANTS = [
    ("base", {}),
    ("wave1", {"leafwise_wave_size": 1}),
    ("dp_f32", {"gpu_use_dp": True}),
]


def train(over):
    cfg = Config.from_dict({**BASE, **over})
    ds = BinnedDataset.from_numpy(Xm, label=ym, config=cfg)
    dv = BinnedDataset.from_numpy(Xmv, label=ymv, config=cfg, reference=ds)
    gb = create_boosting(cfg, ds)
    gb.add_valid(dv, "test")
    t0 = time.time()
    gb.train_iters(IT)
    jax.device_get(gb._train_scores.score)
    wall = time.time() - t0
    mll = None
    for (_, name, value, _) in gb.eval_valid():
        if name == "multi_logloss":
            mll = float(value)
    return gb.materialize_host_trees(), mll, wall


def first_divergence(trees_a, trees_b):
    """(tree_idx, node_idx, {a, b}) of the first structural difference, or
    None when every tree matches node-for-node."""
    for ti, (a, b) in enumererate_safe(trees_a, trees_b):
        na, nb = a.num_leaves - 1, b.num_leaves - 1
        for ni in range(max(na, nb)):
            da = _node(a, ni) if ni < na else None
            db = _node(b, ni) if ni < nb else None
            if da != db:
                return {"tree": ti, "node": ni, "a": da, "b": db}
    return None


def enumererate_safe(xs, ys):
    return enumerate(zip(xs, ys))


def _node(t, i):
    return {"feature": int(t.split_feature[i]),
            "threshold_bin": int(t.threshold_bin[i]),
            "gain": round(float(t.split_gain[i]), 6)}


Xm, ym = make_multiclass_data(N, 10, CLS)
Xmv, ymv = make_multiclass_data(NV, 11, CLS)

base_trees = None
base_mll = None
for name, over in VARIANTS:
    trees, mll, wall = train(over)
    rec = {"variant": name, "rows": N, "iters": IT,
           "mlogloss": round(mll, 6) if mll is not None else None,
           "wall_s": round(wall, 2)}
    if name == "base":
        base_trees, base_mll = trees, mll
    else:
        if mll is not None and base_mll is not None:
            rec["mlogloss_delta_vs_base"] = round(mll - base_mll, 6)
        div = first_divergence(base_trees, trees)
        rec["first_divergent_tree"] = div["tree"] if div else None
        rec["divergence"] = div
    print(json.dumps(rec), flush=True)
